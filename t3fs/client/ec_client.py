"""Erasure-coded storage client: RS(k+m) stripes over a chain group.

This is the capability t3fs ADDS over the reference (BASELINE.json configs
#3/#4): the reference has EC only as a *placement* option in its chain-table
solver (deploy/data_placement/src/model/data_placement.py:484) with no
encode/decode data path.  Here a stripe of k data chunks gets m parity
chunks, each of the k+m shards on a different chain (replication factor 1 —
parity replaces replication), encoded/decoded by the word-packed Pallas
kernels (t3fs.client.ec_codec — the same configuration bench.py measures)
on the co-located TPU, with concurrent stripes micro-batched per launch.
Reconstruction runs the fused decode+verify step: one launch rebuilds the
missing shards AND returns their CRC32Cs, which repair write-back hands to
write_chunk so rebuilt full chunks skip the host crc32c entirely.

Addressing: data chunk j of stripe s  -> ChunkId(inode, s*k + j)
            parity chunk p of stripe s -> ChunkId(inode | PARITY_NS, s*m + p)
Chain placement walks the layout's chain list stripe-by-stripe so recovery
load spreads (the data_placement balanced-design goal).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field

import numpy as np

from t3fs.client.ec_codec import ECCodec
from t3fs.ops.msr import default_msr, msr_code_id
from t3fs.ops.rs import default_rs
from t3fs.storage.types import ChunkId, IOResult, ReadIO, UpdateType
from t3fs.utils import tracing
from t3fs.utils.serde import serde_struct
from t3fs.utils.status import StatusCode, StatusError, make_error

log = logging.getLogger("t3fs.client.ec")

PARITY_NS = 1 << 62   # parity chunk-id namespace bit
LOCAL_NS = 1 << 61    # local-group (LRC) parity chunk-id namespace bit

# Single source of truth for ECLayout.local_scheme values.  Layout
# validation, chunk-id namespacing (num_local_groups decides whether
# LOCAL_NS chunks exist), and the admin `gen-chains` help text all read
# THIS tuple, so adding a scheme cannot skew the three.
SUPPORTED_LOCAL_SCHEMES = ("", "lrc-xor", "pm-msr")
# The subset that adds local-group parity chunks in the LOCAL_NS
# namespace; pm-msr keeps the plain k+m slot set (its repair savings come
# from sub-packetization, not extra parity chunks).
GROUP_PARITY_SCHEMES = ("lrc-xor",)


def subshard_r(chunk_size: int, r_max: int = 4) -> int:
    """Sub-shard split factor for reduced-read repair: the largest r <= r_max
    with chunk_size % r == 0 and a 512-multiple slice (so every sub-shard
    stays on the fused word-kernel path and CRC segment grid).  r > 1 frames
    each helper read as r smaller ReadIOs — finer pacing quanta for the
    scrub budget and natural micro-batch shape for the repair kernel."""
    r = r_max
    while r > 1 and (chunk_size % r or (chunk_size // r) % 512):
        r -= 1
    return r


# Format id assumed for layouts serialized before code_id existed: the
# round-1 generator was row-reduced Vandermonde over the default polynomial.
# Deserializing such a blob must NOT inherit the current default generator —
# decoding rrvand parity with the raid6 matrix reconstructs garbage silently.
LEGACY_CODE_ID = "rrvand-11d"


@serde_struct
@dataclass
class ECLayout:
    k: int = 8
    m: int = 2
    chunk_size: int = 1 << 20
    chains: list[int] = field(default_factory=list)   # >= k+m distinct chains
    # parity format id (RSCode.code_id): persisted with the layout so a
    # change of generator coefficients fails LOUDLY at decode time instead
    # of silently reconstructing garbage from old parity.  Dataclass default
    # (= what a pre-versioning serialized layout deserializes to) is the
    # LEGACY id; new layouts get the current id via create().
    code_id: str = LEGACY_CODE_ID
    # Opt-in LRC local parities (ROADMAP item 4, "regenerating/LRC-style"):
    # "" = pure RS(k+m) (every pre-existing layout deserializes to this);
    # "lrc-xor" partitions the k+m base shards into contiguous groups of
    # ~local_group_size and stores one XOR parity chunk per group (in the
    # LOCAL_NS namespace, rotated onto chains like any other shard).  A
    # single lost shard then rebuilds from its GROUP (group_size reads)
    # instead of k survivors — the repair-bandwidth trade bought with
    # G/(k+m) extra storage.  Scalar-MDS information theory forces the
    # trade: ANY (k+m, k) MDS code needs >= k full shards' worth of bytes
    # per single-shard repair under raw reads (see docs/codec_economics.md).
    # "pm-msr" sidesteps that bound by sub-packetizing: each shard is
    # alpha = 2^((k+m)/2) sub-chunks of a coupled-layer MSR code
    # (ops/msr.py), data shards stay RAW bytes (systematic — healthy
    # first-k reads are byte-identical to plain RS), and a single lost
    # shard rebuilds from every survivor's beta = alpha/2 selected
    # sub-chunks: d*beta/alpha = 0.5625x of k full chunks, at the SAME
    # 1.25x storage (no extra parity chunks — slots == k+m).
    local_scheme: str = ""
    local_group_size: int = 3

    def __post_init__(self):
        if len(self.chains) < self.slots:
            raise make_error(
                StatusCode.INVALID_ARG,
                f"EC({self.k}+{self.m}"
                f"{'+' + str(self.num_local_groups) + 'l' if self.local_scheme else ''}"
                f") needs >= {self.slots} chains")
        if self.local_scheme not in SUPPORTED_LOCAL_SCHEMES:
            raise make_error(
                StatusCode.INVALID_ARG,
                f"unknown local scheme {self.local_scheme!r} "
                f"(supported: {SUPPORTED_LOCAL_SCHEMES})")
        if self.local_scheme == "pm-msr":
            try:
                code = default_msr(self.k, self.m)
            except ValueError as e:
                raise make_error(StatusCode.INVALID_ARG, str(e)) from e
            if self.chunk_size % code.alpha:
                raise make_error(
                    StatusCode.INVALID_ARG,
                    f"pm-msr sub-packetization needs chunk_size divisible "
                    f"by alpha={code.alpha} (got {self.chunk_size})")

    @classmethod
    def create(cls, k: int = 8, m: int = 2, chunk_size: int = 1 << 20,
               chains: list[int] | None = None, local_scheme: str = "",
               local_group_size: int = 3) -> "ECLayout":
        """Layout-creation factory: stamps the CURRENT parity format id
        (the pm-msr coupled generator has its OWN id — its parity bytes
        are not plain RS parity)."""
        if local_scheme == "pm-msr":
            try:
                code_id = msr_code_id(k, m)
            except ValueError as e:
                raise make_error(StatusCode.INVALID_ARG, str(e)) from e
        else:
            code_id = default_rs(k, m).code_id
        return cls(k=k, m=m, chunk_size=chunk_size, chains=chains or [],
                   code_id=code_id,
                   local_scheme=local_scheme,
                   local_group_size=local_group_size)

    @property
    def num_local_groups(self) -> int:
        if self.local_scheme not in GROUP_PARITY_SCHEMES:
            return 0
        return -(-(self.k + self.m) // self.local_group_size)

    @property
    def slots(self) -> int:
        """Chain-rotation period: base shards + one slot per local parity."""
        return self.k + self.m + self.num_local_groups

    def local_groups(self) -> list[tuple[int, ...]]:
        """Balanced contiguous partition of the k+m base shards, e.g.
        10 shards at group size 3 -> (0,1,2) (3,4,5) (6,7) (8,9)."""
        n, g = self.k + self.m, self.num_local_groups
        if not g:
            return []
        base, rem = divmod(n, g)
        out, at = [], 0
        for i in range(g):
            size = base + (1 if i < rem else 0)
            out.append(tuple(range(at, at + size)))
            at += size
        return out

    def group_of(self, shard: int) -> int:
        """Local group index of a base shard (0..k+m-1)."""
        for g, members in enumerate(self.local_groups()):
            if shard in members:
                return g
        raise make_error(StatusCode.INVALID_ARG,
                         f"shard {shard} has no local group")

    def check_code(self, rs) -> None:
        if rs.code_id != self.code_id:
            raise make_error(
                StatusCode.EC_FORMAT_MISMATCH,
                f"stripe parity was written with code {self.code_id!r} but "
                f"this build decodes with {rs.code_id!r} — refusing to mix "
                f"formats")

    def shard_chain(self, stripe: int, shard: int) -> int:
        """Chain of slot `shard` (0..slots-1: base shards, then one slot per
        local-group parity) of a stripe; rotates per stripe."""
        n = len(self.chains)
        return self.chains[(stripe * self.slots + shard) % n]

    def data_chunk(self, inode: int, stripe: int, j: int) -> ChunkId:
        return ChunkId(inode, stripe * self.k + j)

    def parity_chunk(self, inode: int, stripe: int, p: int) -> ChunkId:
        return ChunkId(inode | PARITY_NS, stripe * self.m + p)

    def local_chunk(self, inode: int, stripe: int, g: int) -> ChunkId:
        return ChunkId(inode | LOCAL_NS,
                       stripe * self.num_local_groups + g)

    def shard_chunk(self, inode: int, stripe: int, s: int) -> ChunkId:
        """ChunkId of slot s: data, RS parity, or local-group parity."""
        if s < self.k:
            return self.data_chunk(inode, stripe, s)
        if s < self.k + self.m:
            return self.parity_chunk(inode, stripe, s - self.k)
        return self.local_chunk(inode, stripe, s - self.k - self.m)

    def data_file_layout(self):
        """A FileLayout whose chain_of() reproduces THIS layout's data-chunk
        placement: data chunk idx (= stripe*k + j) lives on
        chains[((idx//k)*slots + idx%k) % n], which is periodic in idx with
        period k*n — so plain StorageClient.read_file_ranges serves healthy
        EC reads (e.g. resharded checkpoint restore) with no EC-aware
        plumbing; only stripes with failed shards need read_stripe."""
        from t3fs.client.layout import FileLayout
        n = len(self.chains)
        chains = [self.chains[((i // self.k) * self.slots + i % self.k) % n]
                  for i in range(self.k * n)]
        return FileLayout(chunk_size=self.chunk_size, chains=chains)


@dataclass
class StripeEncoding:
    """One encoded stripe, ready to write shard-by-shard: the k data shards
    (tail-trimmed to their true lengths; b"" for zero holes) followed by the
    m full-size parity shards — and, when the layout carries a local scheme,
    one full-size XOR local parity per group — with the CRC32C each chunk
    will carry once stored (device-computed by the fused encode+CRC step for
    full shards; host crc32c only for the at-most-one trimmed tail shard;
    0 for holes)."""
    lens: list[int]             # per data shard true length (0 = hole)
    contents: list[bytes]       # `slots` stored contents in slot order
    crcs: list[int]             # CRC32C of contents[i]; 0 for holes


@dataclass
class RepairIOStats:
    """Per-run repair IO accounting (RepairDriver/scrub surface): how many
    bytes came off the wire to rebuild how many, and which path served."""
    bytes_read: int = 0         # survivor/helper payload bytes fetched
    bytes_repaired: int = 0     # rebuilt bytes written back
    sub_reads: int = 0          # sub-range helper ReadIOs issued
    reduced_shards: int = 0     # shards rebuilt by the reduced-read path
    fallback_shards: int = 0    # shards that fell back to full-k decode


class ChainAdmission:
    """Per-chain admission window: bounds in-flight chunk writes per chain so
    one slow chain backpressures only its own shards, not the whole fan-out
    (the checkpoint writer's per-chain window; the fleet-wide stripe window
    is the caller's own semaphore)."""

    def __init__(self, per_chain: int = 2):
        self.per_chain = per_chain
        self._sems: dict[int, asyncio.Semaphore] = {}

    def sem(self, chain_id: int) -> asyncio.Semaphore:
        sem = self._sems.get(chain_id)
        if sem is None:
            sem = self._sems[chain_id] = asyncio.Semaphore(self.per_chain)
        return sem


class ECStorageClient:
    """Stripe-granular EC write/read/repair over a StorageClient."""

    def __init__(self, storage_client, use_device_codec: bool = True,
                 fast_read_retries: int = 4, codec: "ECCodec | None" = None):
        self.sc = storage_client
        self.use_device = use_device_codec
        # device path: the word-packed Pallas kernels (bench.py's measured
        # configuration) with stripe micro-batching; None = numpy oracle
        self.codec = (codec or ECCodec()) if use_device_codec else None
        # degraded reads must not wait out long retry tails on dead chains:
        # parity covers a fast-failed shard, so EC reads use a bounded-retry
        # view of the same client (shared sockets + routing), falling back
        # to the patient client only when reconstruction lacks shards
        self._fast = self._bounded_view(storage_client, fast_read_retries)

    @staticmethod
    def _bounded_view(sc, max_retries: int):
        import copy

        fast = copy.copy(sc)
        fast.cfg = copy.copy(sc.cfg)
        fast.cfg.max_retries = max_retries
        fast.cfg.retry_backoff_s = min(sc.cfg.retry_backoff_s, 0.03)
        return fast

    def _routed_out(self, chain_id: int) -> bool:
        """True when CURRENT routing shows no serving target for the chain:
        a read could only burn its whole retry/backoff budget, so degraded
        paths count the shard as lost immediately.  A stale verdict is safe
        — the patient wave in _reconstruct_shards re-reads want-shards
        directly and recovers them without decoding."""
        chain = self.sc.routing().chain(chain_id)
        return chain is None or not chain.serving()

    # --- codec (Pallas word kernels by default; numpy oracle fallback) ---
    # Device calls go through ECCodec: concurrent stripes micro-batch into
    # one kernel launch on the codec's own thread (XLA compile takes
    # seconds and compute releases the GIL — nothing blocks the loop).

    async def _encode(self, data_shards: np.ndarray, k: int, m: int) -> np.ndarray:
        if self.codec is not None:
            return await self.codec.encode(data_shards, k, m)
        return await asyncio.to_thread(default_rs(k, m).encode_ref,
                                       data_shards)

    async def _encode_verified(self, data_shards: np.ndarray, k: int, m: int
                               ) -> tuple[np.ndarray, np.ndarray | None]:
        """Encode + shard CRCs in ONE device launch (the fused encode+CRC
        step); the numpy oracle has no fused CRC, so it returns None and
        callers fall back to the host crc32c."""
        if self.codec is not None:
            return await self.codec.encode_verified(data_shards, k, m)
        return await self._encode(data_shards, k, m), None

    async def _reconstruct(self, present_rows: np.ndarray,
                           present: tuple[int, ...], want: tuple[int, ...],
                           k: int, m: int) -> np.ndarray:
        if self.codec is not None:
            return await self.codec.reconstruct(present_rows, present, want,
                                                k, m)

        def run():
            shards = {idx: present_rows[i] for i, idx in enumerate(present)}
            return default_rs(k, m).decode_ref(shards, list(want))
        return await asyncio.to_thread(run)

    async def _reconstruct_verified(self, present_rows: np.ndarray,
                                    present: tuple[int, ...],
                                    want: tuple[int, ...], k: int, m: int
                                    ) -> tuple[np.ndarray, np.ndarray | None]:
        """Decode + shard CRCs in ONE device launch (the fused
        decode+verify step); the numpy oracle has no fused CRC, so it
        returns None and callers fall back to the host crc32c."""
        if self.codec is not None:
            return await self.codec.reconstruct_verified(
                present_rows, present, want, k, m)
        return await self._reconstruct(present_rows, present, want,
                                       k, m), None

    async def _msr_encode_verified(self, data_shards: np.ndarray, k: int,
                                   m: int
                                   ) -> tuple[np.ndarray, np.ndarray | None]:
        """pm-msr twin of _encode_verified: coupled-layer parity + fused
        shard CRCs in one launch; numpy oracle (no fused CRC) fallback."""
        if self.codec is not None:
            return await self.codec.msr_encode_verified(data_shards, k, m)
        code = default_msr(k, m)
        return await asyncio.to_thread(code.encode_np, data_shards), None

    async def _msr_decode_verified(self, present_rows: np.ndarray,
                                   present: tuple[int, ...],
                                   want: tuple[int, ...], k: int, m: int
                                   ) -> tuple[np.ndarray, np.ndarray | None]:
        """pm-msr twin of _reconstruct_verified: the multi-loss/degraded
        full-k decode (exactly k survivor shards — never more than RS)."""
        if self.codec is not None:
            return await self.codec.msr_decode_verified(
                present_rows, present, want, k, m)
        code = default_msr(k, m)
        return await asyncio.to_thread(
            code.decode_np, present, present_rows, want), None

    async def _msr_repair_eval(self, helper_rows: np.ndarray, f: int,
                               k: int, m: int) -> tuple[np.ndarray, int]:
        """One fused pm-msr projection rebuild: (d, beta_len) helper rows
        -> (full rebuilt chunk, device CRC32C of the whole chunk)."""
        if self.codec is not None:
            out, crc = await self.codec.msr_repair(helper_rows, f, k, m)
            return out, int(crc)
        from t3fs.ops.codec import crc32c
        code = default_msr(k, m)
        sub = 2 * helper_rows.shape[-1] // code.alpha

        def run():
            subs = helper_rows.reshape(code.d, code.alpha // 2, sub)
            out = code.repair_np(f, subs)
            return out, crc32c(out.tobytes())
        return await asyncio.to_thread(run)

    async def close(self) -> None:
        if self.codec is not None:
            await self.codec.close()

    # --- write ---

    async def encode_stripe(self, layout: ECLayout, data: bytes
                            ) -> StripeEncoding:
        """Encode one stripe's data into its k+m stored shard contents plus
        the CRC32C each chunk will carry — via the fused encode+CRC step, so
        full shards (the hot path) never touch the host crc32c.  The result
        feeds write_encoded (possibly more than once: retries / resumed
        saves rewrite a shard subset without re-encoding)."""
        k, m, cs = layout.k, layout.m, layout.chunk_size
        assert len(data) <= k * cs
        lens = [max(0, min(cs, len(data) - j * cs)) for j in range(k)]
        arr = np.zeros((k, cs), dtype=np.uint8)
        flat = np.frombuffer(data, dtype=np.uint8)
        for j in range(k):
            if lens[j]:
                arr[j, :lens[j]] = flat[j * cs: j * cs + lens[j]]
        if layout.local_scheme == "pm-msr":
            layout.check_code(default_msr(k, m))
            parity, dev_crcs = await self._msr_encode_verified(arr, k, m)
        else:
            layout.check_code(default_rs(k, m))
            parity, dev_crcs = await self._encode_verified(arr, k, m)

        from t3fs.ops.codec import crc32c
        contents: list[bytes] = []
        crcs: list[int] = []
        for j in range(k):
            content = bytes(arr[j, :lens[j]]) if lens[j] else b""
            contents.append(content)
            if lens[j] == 0:
                crcs.append(0)
            elif lens[j] == cs and dev_crcs is not None:
                crcs.append(int(dev_crcs[j]))
            else:
                # trimmed tail shard: the device CRC covers the padded full
                # chunk, not the stored bytes (at most one per file — cold)
                crcs.append(crc32c(content))
        for p in range(m):
            contents.append(bytes(parity[p]))
            crcs.append(int(dev_crcs[k + p]) if dev_crcs is not None
                        else crc32c(contents[-1]))
        if layout.num_local_groups:
            # local XOR parities over the PADDED member buffers (consistent
            # with absent == zeros on the repair side); the all-ones repair
            # program is exactly an XOR fold + CRC, so the device path
            # reuses it — local groups micro-batch alongside stripe encodes
            full = np.concatenate([arr, parity], axis=0)     # (k+m, cs)

            async def one_local(members: tuple[int, ...]) -> tuple[bytes, int]:
                rows = np.ascontiguousarray(full[list(members)])
                if self.codec is not None:
                    out, crc = await self.codec.repair(
                        rows, (1,) * len(members), k, m)
                    return bytes(out), int(crc)
                buf = rows[0].copy()
                for extra in rows[1:]:
                    buf ^= extra
                return bytes(buf), crc32c(buf.tobytes())

            for content, crc in await asyncio.gather(
                    *(one_local(g) for g in layout.local_groups())):
                contents.append(content)
                crcs.append(crc)
        return StripeEncoding(lens=lens, contents=contents, crcs=crcs)

    async def write_stripe(self, layout: ECLayout, inode: int, stripe: int,
                           data: bytes,
                           shards: tuple[int, ...] | None = None
                           ) -> list[IOResult]:
        """Write one full stripe (k*chunk_size bytes; shorter data is
        zero-padded on the wire but chunk lengths preserve the true size).
        Returns per-shard IOResults aligned with `shards` (default: all k+m,
        data shards first then parity) — a partial failure names exactly the
        shards to retry, via write_encoded, without rewriting the stripe."""
        enc = await self.encode_stripe(layout, data)
        return await self.write_encoded(layout, inode, stripe, enc, shards)

    async def write_encoded(self, layout: ECLayout, inode: int, stripe: int,
                            enc: StripeEncoding,
                            shards: tuple[int, ...] | None = None,
                            admission: ChainAdmission | None = None
                            ) -> list[IOResult]:
        """Write a subset of an encoded stripe's shards (default all k+m).
        Results align with `shards` order, so callers retry exactly the
        failed entries.  Stored CRCs ride along as write_chunk checksums:
        the server cross-checks the payload against the device-computed CRC
        and the host crc32c never runs.

        Whole-chunk REPLACE (not splice-write) so a shorter re-write of the
        stripe cannot leave stale tail bytes that disagree with the new
        parity; shards emptied by the re-write are REMOVEd for the same
        reason (absent == zeros is the decode contract)."""
        k, m, cs = layout.k, layout.m, layout.chunk_size
        if shards is None:
            shards = tuple(range(layout.slots))

        async def one(s: int) -> IOResult:
            chain = layout.shard_chain(stripe, s)
            cid = layout.shard_chunk(inode, stripe, s)
            if s < k and enc.lens[s] == 0:
                kwargs = dict(update_type=UpdateType.REMOVE)
                content: bytes = b""
            else:
                kwargs = dict(update_type=UpdateType.REPLACE,
                              checksum=enc.crcs[s])
                content = enc.contents[s]
            if admission is None:
                return await self.sc.write_chunk(chain, cid, 0, content,
                                                 chunk_size=cs, **kwargs)
            async with admission.sem(chain):
                return await self.sc.write_chunk(chain, cid, 0, content,
                                                 chunk_size=cs, **kwargs)

        return list(await asyncio.gather(*(one(s) for s in shards)))

    # --- read with reconstruct-on-unavailability ---

    async def read_stripe(self, layout: ECLayout, inode: int, stripe: int,
                          stripe_len: int) -> bytes:
        """Read a stripe's data, reconstructing any unavailable data chunks
        from surviving shards (the EC-decode recovery path, BASELINE #4)."""
        data, _crcs = await self.read_stripe_with_crcs(layout, inode, stripe,
                                                       stripe_len)
        return data

    async def read_stripe_with_crcs(self, layout: ECLayout, inode: int,
                                    stripe: int, stripe_len: int
                                    ) -> tuple[bytes, list[int | None]]:
        """read_stripe + per-data-shard CRC32C of the STORED chunk content,
        aligned with shard index 0..k-1: a directly-read shard reports the
        storage layer's stored CRC (IOResult.checksum); a reconstructed full
        shard reports the fused decode+verify step's device CRC; None where
        neither applies (zero holes, trimmed reconstructed tails, the numpy
        oracle).  Manifest-verified restores (t3fs.ckpt) compare these
        against committed CRCs without hashing a byte on the host.

        First-k fan-out: ALL k+m shards are requested concurrently and the
        read completes as soon as every live data shard has landed OR any k
        shards (zero holes count for free) can feed the fused decode+verify
        step — a straggling data shard becomes an erasure the parity
        covers, never a wait."""
        k, m, cs = layout.k, layout.m, layout.chunk_size
        lens = [max(0, min(cs, stripe_len - j * cs)) for j in range(k)]
        zero_shards = frozenset(j for j in range(k) if lens[j] == 0)
        needed = [j for j in range(k) if lens[j]]
        got: dict[int, tuple[bytes, int]] = {}   # shard -> (content, crc)
        tasks: dict[asyncio.Task, int] = {}
        for s in range(k + m):
            if s < k and lens[s] == 0:
                continue   # zero hole: free decode input, never read
            chain = layout.shard_chain(stripe, s)
            if self._routed_out(chain):
                continue   # fast-fail: no serving target routed
            cid = (layout.data_chunk(inode, stripe, s) if s < k
                   else layout.parity_chunk(inode, stripe, s - k))
            t = asyncio.create_task(self._fast.batch_read(
                [ReadIO(chunk_id=cid, chain_id=chain)]))
            tasks[t] = s
        pending = set(tasks)
        try:
            while pending:
                if all(j in got for j in needed):
                    break
                if len(got) + len(zero_shards) >= k:
                    break
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED)
                for t in done:
                    try:
                        # t3fslint: allow(blocking-in-async) — t is a member of asyncio.wait's done set — result() cannot block
                        results, payloads = t.result()
                    except StatusError:
                        continue   # transport failure == shard missing
                    r = results[0]
                    if r.status.code == int(StatusCode.OK):
                        got[tasks[t]] = (payloads[0], int(r.checksum))
        finally:
            for t in pending:
                t.cancel()
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)
        chunks: dict[int, bytes] = {}
        crcs: dict[int, int | None] = {}
        for j in needed:
            if j in got:
                chunks[j], crcs[j] = got[j]
        missing = tuple(j for j in needed if j not in got)
        if missing:
            have: dict[int, np.ndarray] = {}
            for s, (content, _crc) in got.items():
                buf = np.zeros(cs, dtype=np.uint8)
                buf[: len(content)] = np.frombuffer(content, dtype=np.uint8)
                have[s] = buf
            for j in zero_shards:
                have[j] = np.zeros(cs, dtype=np.uint8)
            if len(have) >= k:
                # enough landed before the stragglers: decode right here
                # from what the fan-out already paid for
                rec, rcrcs = await self._decode_from(layout, have,
                                                     missing, k, m)
            else:
                # the fan-out drained short of k: patient path (re-reads
                # survivors AND want-shards with full retry budget)
                rec, rcrcs = await self._reconstruct_shards(
                    layout, inode, stripe, missing, zero_shards,
                    known={s: content for s, (content, _) in got.items()})
            for j, content, rc in zip(missing, rec, rcrcs):
                chunks[j] = content[: lens[j]]
                # the device CRC covers the full chunk: it matches the
                # stored-content CRC only for untrimmed shards
                crcs[j] = rc if lens[j] == cs else None
        return (b"".join(chunks[j][: lens[j]].ljust(lens[j], b"\x00")
                         for j in range(k) if lens[j]),
                [crcs.get(j) for j in range(k)])

    async def _reconstruct_shards(self, layout: ECLayout, inode: int,
                                  stripe: int, want: tuple[int, ...],
                                  zero_shards: frozenset[int],
                                  known: dict[int, bytes] | None = None,
                                  prefer: tuple[int, ...] | None = None,
                                  stats: RepairIOStats | None = None
                                  ) -> tuple[list[bytes], list[int | None]]:
        """Fetch enough surviving shards (data we already have + parity +
        other data) and decode the wanted shard indices (0..k+m-1 space).
        Returns (contents, crcs) aligned with `want`: crc is the DEVICE
        CRC32C of the full-chunk content when the fused decode+verify step
        produced the shard, else None (directly-recovered / oracle path).

        `zero_shards` lists data shards the CALLER knows were never written
        (short stripe) — only those may be substituted with zeros on
        CHUNK_NOT_FOUND.  Any other missing shard counts as lost; silently
        zero-filling it would decode garbage and, on the repair path, write
        that garbage back as if it were real (double-loss corruption).

        `prefer` restricts the FAST pass to those survivor shard indices
        (the repair planner's load-balanced k-pick); the patient retry
        wave ignores it, so a failed preferred read degrades to extra IO,
        never to a failed repair."""
        k, m, cs = layout.k, layout.m, layout.chunk_size
        known = dict(known or {})
        have: dict[int, np.ndarray] = {}
        for j, content in known.items():
            buf = np.zeros(cs, dtype=np.uint8)
            buf[: len(content)] = np.frombuffer(content, dtype=np.uint8)
            have[j] = buf

        # zero-hole shards bypass `prefer`: they cost no IO (substituted,
        # never read) and the patient wave never materializes them
        need_more = [s for s in range(k + m)
                     if s not in have and s not in want
                     and (prefer is None or s in prefer
                          or s in zero_shards)]
        ios, ids = [], []
        for s in need_more:
            if s in zero_shards:
                have[s] = np.zeros(cs, dtype=np.uint8)
                continue
            if self._routed_out(layout.shard_chain(stripe, s)):
                continue              # fast-fail; patient wave may still try
            cid = (layout.data_chunk(inode, stripe, s) if s < k
                   else layout.parity_chunk(inode, stripe, s - k))
            ios.append(ReadIO(chunk_id=cid,
                              chain_id=layout.shard_chain(stripe, s)))
            ids.append(s)
        if ios:
            results, payloads = await self._fast.batch_read(ios)
            for s, r, p in zip(ids, results, payloads):
                if r.status.code == int(StatusCode.OK):
                    if stats is not None:
                        stats.bytes_read += len(p)
                    buf = np.zeros(cs, dtype=np.uint8)
                    buf[: len(p)] = np.frombuffer(p, dtype=np.uint8)
                    have[s] = buf
        if len(have) < k:
            # not enough survivors after the fast pass: one PATIENT retry
            # wave over everything still missing — including the `want`
            # shards themselves (a transient blip, e.g. a reshape in
            # progress, may have fast-failed shards that a patient read
            # recovers directly, needing no decode at all)
            ios2, ids2 = [], []
            for s in range(k + m):
                if s in have or s in zero_shards:
                    continue
                cid = (layout.data_chunk(inode, stripe, s) if s < k
                       else layout.parity_chunk(inode, stripe, s - k))
                ios2.append(ReadIO(chunk_id=cid,
                                   chain_id=layout.shard_chain(stripe, s)))
                ids2.append(s)
            if ios2:
                results2, payloads2 = await self.sc.batch_read(ios2)
                for s, r, p in zip(ids2, results2, payloads2):
                    if r.status.code == int(StatusCode.OK):
                        if stats is not None:
                            stats.bytes_read += len(p)
                        buf = np.zeros(cs, dtype=np.uint8)
                        buf[: len(p)] = np.frombuffer(p, dtype=np.uint8)
                        have[s] = buf
        if len(have) < k:
            raise make_error(
                StatusCode.TARGET_OFFLINE,
                f"EC stripe {stripe}: only {len(have)} of {k + m} shards "
                f"available, need {k}")
        return await self._decode_from(layout, have, want, k, m)

    async def _decode_from(self, layout: ECLayout,
                           have: dict[int, np.ndarray],
                           want: tuple[int, ...], k: int, m: int
                           ) -> tuple[list[bytes], list[int | None]]:
        """Decode `want` shard indices from >= k available full-chunk-size
        buffers (`have`, keyed in 0..k+m shard space — zero holes included
        as zero buffers).  Returns (contents, crcs) aligned with `want`;
        crc is the fused decode+verify step's device CRC32C of the
        full-chunk content when that step produced the shard, else None.
        Want-shards already in `have` pass through without decoding."""
        msr = layout.local_scheme == "pm-msr"
        layout.check_code(default_msr(k, m) if msr else default_rs(k, m))
        # shards recovered directly need no decoding
        still_want = tuple(s for s in want if s not in have)
        decoded: dict[int, bytes] = {}
        crc_of: dict[int, int] = {}
        if still_want:
            # recovered want-shards may serve as decode inputs; only the
            # still-missing ones must stay out of the present set
            present = tuple(sorted(s for s in have.keys()
                                   if s not in still_want)[:k])
            rows = np.stack([have[s] for s in present])
            if msr:
                out, crcs = await self._msr_decode_verified(
                    rows, present, still_want, k, m)
            else:
                out, crcs = await self._reconstruct_verified(
                    rows, present, still_want, k, m)
            decoded = {s: bytes(out[i]) for i, s in enumerate(still_want)}
            if crcs is not None:
                # fused-step layout: k survivor CRCs, then the rebuilt
                # shards' CRCs in still_want order
                crc_of = {s: int(crcs[k + i])
                          for i, s in enumerate(still_want)}
        return ([decoded[s] if s in decoded else bytes(have[s])
                 for s in want],
                [crc_of.get(s) for s in want])

    # --- reduced-read repair (the ISSUE 9 bandwidth path) ---

    def hot_repair_programs(self, layout: ECLayout) -> list[tuple[int, ...]]:
        """The coefficient rows single-shard repair will actually run under
        this layout — the warmup set.  With a local scheme: one all-ones
        program per group size (member and local rebuilds share it).
        Without: the k+m scheduled single-row programs over the canonical
        (no-holes, no-preference) survivor pick _plan_reduced makes."""
        rows: dict[tuple[int, ...], None] = {}
        if layout.local_scheme == "pm-msr":
            return []   # projection schedules precompile via warmup_msr
        if layout.local_scheme:
            for members in layout.local_groups():
                rows[(1,) * len(members)] = None
        else:
            base = layout.k + layout.m
            for s in range(base):
                plan = self._plan_reduced(layout, s, frozenset((s,)),
                                          frozenset(), None)
                if plan:
                    rows[tuple(c for _slot, c in plan)] = None
        return list(rows)

    def warmup_repair(self, layout: ECLayout,
                      batch_sizes: tuple[int, ...] = (1,)) -> None:
        """Precompile this layout's repair programs at the sub-shard length
        the reduced path uses (and, with a local scheme, at full chunk size
        for the encode-side local XOR) — RepairDriver-setup hook, so the
        first drill stripe never eats the Mosaic compile (satellite of the
        same bug class warmup_decode fixed for degraded reads)."""
        if self.codec is None:
            return
        k, m, cs = layout.k, layout.m, layout.chunk_size
        if layout.local_scheme == "pm-msr":
            # each failed slot has its own projection schedule, so the
            # warmup set is one fused repair step per slot + the coupled
            # encode step (codec.warmup_msr)
            self.codec.warmup_msr(list(range(k + m)), cs, k, m, batch_sizes)
            return
        rows = self.hot_repair_programs(layout)
        sub = cs // subshard_r(cs)
        self.codec.warmup_repair(rows, sub, k, m, batch_sizes)
        if layout.local_scheme and sub != cs:
            self.codec.warmup_repair(rows, cs, k, m, batch_sizes)

    def _plan_reduced(self, layout: ECLayout, s: int,
                      lost: frozenset[int], zero_shards: frozenset[int],
                      read_shards: tuple[int, ...] | None
                      ) -> list[tuple[int, int]] | None:
        """Helper plan [(slot, gf_coeff), ...] rebuilding lost slot s with
        fewer than k full-chunk reads, or None when only the full-k decode
        applies.  Zero-hole members are pre-dropped (they contribute zero
        bytes for free); an empty plan means the rebuilt content is zeros.

        With a local scheme, a shard whose group (incl. its local parity)
        holds no OTHER loss rebuilds from the group — group_size reads
        instead of k.  Without one, a SINGLE lost shard still rides the
        scheduled single-row program over k survivors: same bytes as full-k,
        but sub-range framed (pacing quanta) and far fewer device ops.

        With "pm-msr", a SINGLE lost slot reads every survivor's repair
        projection — all d = k+m-1 helpers ship beta/alpha of a chunk each
        (0.5625x of k full chunks); coeff 0 marks a zero-hole helper whose
        projection is substituted as zeros without a read.  Multi-loss
        returns None: the joint decode reads exactly k full shards, never
        more than plain RS."""
        k, m = layout.k, layout.m
        base = k + m
        if layout.local_scheme == "pm-msr":
            if len(lost) > 1:
                return None                    # multi-loss: joint decode
            sch = default_msr(k, m).schedule(s)
            return [(x, 0 if x in zero_shards else 1) for x in sch.helpers]
        if layout.local_scheme:
            groups = layout.local_groups()
            if s >= base:                      # lost local parity
                members = groups[s - base]
                if lost & set(members):
                    return None
                return [(x, 1) for x in members if x not in zero_shards]
            g = layout.group_of(s)
            local_slot = base + g
            others = set(groups[g]) - {s} | {local_slot}
            if lost & others:
                return None                    # second loss in the group
            return [(x, 1) for x in sorted(others) if x not in zero_shards]
        if len(lost) > 1:
            return None                        # multi-loss: joint decode
        survivors = [x for x in range(base) if x not in lost]
        # zero holes first (free), then the planner's balanced pick
        pref = set(read_shards or ())

        def rank(x: int) -> tuple:
            return (x not in zero_shards, x not in pref, x)
        present = sorted(survivors, key=rank)[:k]
        row = default_rs(k, m).reconstruct_gfmatrix(sorted(present), [s])[0]
        return [(p, int(c)) for p, c in zip(sorted(present), row)
                if c and p not in zero_shards]

    async def _repair_eval(self, rows: np.ndarray, coeffs: tuple[int, ...],
                           k: int, m: int) -> tuple[bytes, int]:
        if self.codec is not None:
            out, crc = await self.codec.repair(rows, coeffs, k, m)
            return bytes(out), int(crc)
        from t3fs.ops.codec import crc32c
        from t3fs.ops.repair_program import (eval_program_np,
                                             schedule_repair_program)
        rs = default_rs(k, m)

        def run():
            out = eval_program_np(schedule_repair_program(coeffs), rows, rs)
            return bytes(out), crc32c(out.tobytes())
        return await asyncio.to_thread(run)

    async def _repair_reduced(self, layout: ECLayout, inode: int,
                              stripe: int, s: int,
                              plan: list[tuple[int, int]],
                              stats: RepairIOStats
                              ) -> tuple[bytes, int | None] | None:
        """Execute one reduced-repair plan: fetch each helper as r sub-range
        ReadIOs (existing offset/len wire fields — no new format), evaluate
        the scheduled program per sub-shard through the batched codec, and
        stitch the full-chunk CRC with crc32c_combine.  Returns None when
        any helper read fails — the caller falls back to full-k decode."""
        from t3fs.ops.codec import crc32c_combine
        k, m, cs = layout.k, layout.m, layout.chunk_size
        if layout.local_scheme == "pm-msr":
            return await self._repair_msr(layout, inode, stripe, s, plan,
                                          stats)
        if not plan:
            return bytes(cs), None             # all-holes group: zeros
        r = subshard_r(cs)
        sub = cs // r
        ios = []
        for slot, _c in plan:
            for i in range(r):
                ios.append(ReadIO(
                    chunk_id=layout.shard_chunk(inode, stripe, slot),
                    chain_id=layout.shard_chain(stripe, slot),
                    offset=i * sub, length=sub))
        try:
            with tracing.span("ec.repair.subshard_read", helpers=len(plan),
                              sub_reads=len(ios)):
                results, payloads = await self._fast.batch_read(ios)
        except StatusError:
            return None
        h = len(plan)
        bufs = np.zeros((r, h, sub), dtype=np.uint8)
        for j, (res, p) in enumerate(zip(results, payloads)):
            if res.status.code != int(StatusCode.OK):
                return None                    # helper lost too: fall back
            # the server clamps reads past the stored length to SHORT
            # payloads (trimmed tails): zero-pad, absent == zeros
            stats.bytes_read += len(p)
            stats.sub_reads += 1
            hi, i = divmod(j, r)
            bufs[i, hi, : len(p)] = np.frombuffer(p, dtype=np.uint8)
        coeffs = tuple(c for _slot, c in plan)
        parts = await asyncio.gather(
            *(self._repair_eval(bufs[i], coeffs, k, m) for i in range(r)))
        content = b"".join(p for p, _crc in parts)
        crc = parts[0][1]
        for _p, sub_crc in parts[1:]:
            crc = crc32c_combine(crc, sub_crc, sub)
        return content, crc

    async def _repair_msr(self, layout: ECLayout, inode: int, stripe: int,
                          s: int, plan: list[tuple[int, int]],
                          stats: RepairIOStats
                          ) -> tuple[bytes, int | None] | None:
        """Execute one pm-msr projection-repair plan: every live helper
        ships only its beta = alpha/2 selected sub-chunks — merged into
        contiguous (offset, length) sub-range ReadIOs on the existing
        wire fields, no new RPCs — and the coupled-layer rebuild runs as
        ONE fused device step (stage A/C constant folds around the
        batched stage-B word fold, full-chunk CRC32C fused in).  Returns
        None when any live helper read fails: the caller falls back to
        the full-k joint decode, so a lost helper degrades to RS-cost IO,
        never to a failed repair."""
        k, m, cs = layout.k, layout.m, layout.chunk_size
        code = default_msr(k, m)
        sch = code.schedule(s)
        sub = code.subchunk_len(cs)
        runs = sch.read_runs()
        live = [slot for slot, c in plan if c]     # coeff 0 == zero hole
        ios = []
        for slot in live:
            cid = layout.shard_chunk(inode, stripe, slot)
            chain = layout.shard_chain(stripe, slot)
            for start, count in runs:
                ios.append(ReadIO(chunk_id=cid, chain_id=chain,
                                  offset=start * sub, length=count * sub))
        try:
            with tracing.span("ec.repair.msr_projection",
                              helpers=len(live), sub_reads=len(ios)):
                results, payloads = await self._fast.batch_read(ios)
        except StatusError:
            return None
        # helper rows: ascending slot order, planes in ascending selected-
        # plane order (the codec.msr_repair byte contract); run ri starts
        # at selected-plane position cum[ri]
        cum = [0]
        for _start, count in runs:
            cum.append(cum[-1] + count)
        hidx = {slot: j for j, slot in enumerate(sch.helpers)}
        bufs = np.zeros((code.d, sch.npl * sub), dtype=np.uint8)
        for j, (res, p) in enumerate(zip(results, payloads)):
            if res.status.code != int(StatusCode.OK):
                return None                # helper lost too: fall back
            # short payloads (trimmed tails / reads past the stored
            # length) zero-pad — absent == zeros is the decode contract
            stats.bytes_read += len(p)
            stats.sub_reads += 1
            hi, ri = divmod(j, len(runs))
            off = cum[ri] * sub
            bufs[hidx[live[hi]],
                 off: off + len(p)] = np.frombuffer(p, dtype=np.uint8)
        out, crc = await self._msr_repair_eval(bufs, s, k, m)
        return bytes(out), int(crc)

    async def repair_chunk(self, layout: ECLayout, inode: int, stripe: int,
                           shard: int, stripe_len: int) -> IOResult:
        """Decode-reconstruct one lost shard and write it back to its chain
        (target-resync EC recovery, BASELINE config #4).  stripe_len is the
        stripe's true data length — it determines which shards are legitimate
        zero holes vs genuinely lost."""
        return (await self.repair_stripe(layout, inode, stripe, (shard,),
                                         stripe_len))[0]

    async def repair_stripe(self, layout: ECLayout, inode: int, stripe: int,
                            shards: tuple[int, ...], stripe_len: int,
                            read_shards: tuple[int, ...] | None = None,
                            mode: str = "subshard",
                            stats: RepairIOStats | None = None
                            ) -> list[IOResult]:
        """Repair a stripe's lost shards (slot indices: base shards and,
        with a local scheme, local parities).

        mode="subshard" (default) tries the reduced-read path per shard
        first — LRC group rebuild (group_size reads instead of k) or, lacking
        a scheme, the scheduled single-row program — falling back per shard
        to the joint full-k decode on any helper failure or multi-loss in a
        group.  mode="full" is the classic path: survivors read once, one
        decode produces every wanted shard.

        `read_shards` (RepairDriver's balanced pick) orders the no-scheme
        survivor choice and restricts the full-k FAST pass to those shard
        indices; shortfalls still fall through to the unrestricted patient
        wave.  `stats` accrues bytes_read / bytes_repaired / path counts."""
        with tracing.start_root("ec.repair_stripe", inode=inode,
                                stripe=stripe, shards=len(shards)):
            return await self._repair_stripe_inner(
                layout, inode, stripe, shards, stripe_len, read_shards,
                mode, stats)

    async def _repair_stripe_inner(self, layout: ECLayout, inode: int,
                                   stripe: int, shards: tuple[int, ...],
                                   stripe_len: int,
                                   read_shards: tuple[int, ...] | None,
                                   mode: str,
                                   stats: RepairIOStats | None
                                   ) -> list[IOResult]:
        k, cs = layout.k, layout.chunk_size
        stats = stats if stats is not None else RepairIOStats()
        lens = [max(0, min(cs, stripe_len - j * cs)) for j in range(k)]
        zero_shards = frozenset(j for j in range(k) if lens[j] == 0)
        # zero-hole data shards are never materialized — absent == zeros is
        # the decode contract write_stripe enforces with REMOVE; "repairing"
        # one means ensuring absence, not REPLACE-writing an empty chunk
        holes = [s for s in shards if s in zero_shards]
        lost = tuple(s for s in shards if s not in zero_shards)
        rebuilt: dict[int, tuple[bytes, int | None]] = {}
        if mode == "subshard" and lost:
            lost_set = frozenset(lost)

            async def try_one(s: int) -> None:
                plan = self._plan_reduced(layout, s, lost_set, zero_shards,
                                          read_shards)
                if plan is None:
                    return
                res = await self._repair_reduced(layout, inode, stripe, s,
                                                 plan, stats)
                if res is not None:
                    rebuilt[s] = res
                    stats.reduced_shards += 1

            await asyncio.gather(*(try_one(s) for s in lost))
        remaining = tuple(s for s in lost if s not in rebuilt)
        if remaining:
            stats.fallback_shards += len(remaining)
            # local-parity slots can't ride the RS joint decode: rebuild
            # their group members' XOR directly once the base decode ran
            base_remaining = tuple(s for s in remaining if s < k + layout.m)
            rec, crcs = (await self._reconstruct_shards(
                layout, inode, stripe, base_remaining, zero_shards,
                prefer=read_shards, stats=stats)
                if base_remaining else ([], []))
            for s, c, crc in zip(base_remaining, rec, crcs):
                rebuilt[s] = (c, crc)
            for s in remaining:
                if s in rebuilt:
                    continue
                # lost local parity whose group ALSO lost a member: XOR the
                # group back together from the decode output + survivors
                members = layout.local_groups()[s - k - layout.m]
                plan = [(x, 1) for x in members if x not in zero_shards]
                known = {x: rebuilt[x][0] for x, _ in plan if x in rebuilt}
                need = tuple(x for x, _ in plan if x not in known)
                if need:
                    more, _ = await self._reconstruct_shards(
                        layout, inode, stripe, need, zero_shards,
                        known=known, stats=stats)
                    known.update(dict(zip(need, more)))
                buf = np.zeros(cs, dtype=np.uint8)
                for x, _ in plan:
                    row = np.frombuffer(known[x], dtype=np.uint8)
                    buf[: len(row)] ^= row
                rebuilt[s] = (bytes(buf), None)

        async def write_back(shard: int, content: bytes,
                             crc: int | None) -> IOResult:
            cid = layout.shard_chunk(inode, stripe, shard)
            if shard < k:
                content = content[: lens[shard]]
            if len(content) != cs:
                # truncated data shard: the device CRC covers the full
                # chunk, not the tail-trimmed bytes — let the client re-CRC
                crc = None
            stats.bytes_repaired += len(content)
            return await self.sc.write_chunk(
                layout.shard_chain(stripe, shard), cid, 0, bytes(content),
                chunk_size=cs, update_type=UpdateType.REPLACE,
                checksum=crc)

        async def remove_hole(shard: int) -> IOResult:
            return await self.sc.write_chunk(
                layout.shard_chain(stripe, shard),
                layout.data_chunk(inode, stripe, shard), 0, b"",
                chunk_size=cs, update_type=UpdateType.REMOVE)

        done = dict(zip(lost, await asyncio.gather(
            *(write_back(s, *rebuilt[s]) for s in lost))))
        done.update(zip(holes, await asyncio.gather(
            *(remove_hole(s) for s in holes))))
        return [done[s] for s in shards]
