"""Client libraries: storage, meta, mgmtd (reference: src/client/ — SURVEY.md §2.6)."""
