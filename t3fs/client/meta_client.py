"""MetaClient: typed wrapper over the Meta service with server selection.

Reference analogs: client/meta/MetaClient.{h,cc} (typed ops, retries),
ServerSelectionStrategy.h (random/round-robin with failover across the
stateless meta servers).
"""

from __future__ import annotations

import itertools
import logging
import random
import uuid as uuidlib

from t3fs.meta.acl import UserInfo
from t3fs.meta.schema import DirEntry, Inode, InodeType
from t3fs.utils import serde
from t3fs.utils.status import StatusCode
from t3fs.meta.service import (
    BatchStatReq, EntryReq, InodeReq, LockDirReq, PathReq, PruneSessionReq,
    SetAttrReq,
)
from t3fs.net.client import Client
from t3fs.utils.status import StatusError

log = logging.getLogger("t3fs.client.meta")


class MetaClient:
    def __init__(self, addresses: list[str], client: Client | None = None,
                 client_id: str = "", strategy: str = "roundrobin",
                 max_retries: int = 3, user: UserInfo | None = None):
        assert addresses
        self.addresses = list(addresses)
        self.client = client or Client()
        self.client_id = client_id or f"mc-{random.getrandbits(40):010x}"
        self.strategy = strategy
        self.max_retries = max_retries
        # default identity stamped on every request (None = trusted
        # caller, enforcement off); per-call `user=` overrides it — the
        # FUSE daemon passes each kernel request's caller this way
        self.user = user
        self._rr = itertools.count()

    def _pick(self, attempt: int) -> str:
        if self.strategy == "random" and attempt == 0:
            return random.choice(self.addresses)
        return self.addresses[(next(self._rr) + attempt) % len(self.addresses)]

    async def _call(self, method: str, req, user: UserInfo | None = None):
        ident = user if user is not None else self.user
        if ident is not None and hasattr(req, "user"):
            req.user = ident
        last: StatusError | None = None
        for attempt in range(self.max_retries):
            address = self._pick(attempt)
            try:
                rsp, _ = await self.client.call(address, f"Meta.{method}", req)
                return rsp
            except StatusError as e:
                if not e.status.retryable:
                    raise
                last = e
        raise last

    # --- typed ops ---

    async def stat(self, path: str, follow: bool = True,
                   user: UserInfo | None = None) -> Inode:
        return (await self._call("stat", PathReq(path=path, follow=follow),
                                 user=user)).inode

    async def stat_inode(self, inode_id: int) -> Inode:
        return (await self._call("stat_inode", InodeReq(inode_id=inode_id))).inode

    def _rid(self) -> str:
        """Fresh idempotency key; reused across the retries of ONE logical
        mutation so a replay returns the recorded result (Idempotent.h)."""
        return str(uuidlib.uuid4())

    async def create(self, path: str, perm: int = 0o644, chunk_size: int = 0,
                     stripe: int = 0, write: bool = False,
                     user: UserInfo | None = None) -> tuple[Inode, str]:
        """write=True opens a write session with the create (O_CREAT|O_WRONLY);
        the caller must close(inode_id, session_id) or the session pins GC
        until the dead-client pruner reaps it."""
        rsp = await self._call("create", PathReq(
            path=path, perm=perm, chunk_size=chunk_size, stripe=stripe,
            write=write, client_id=self.client_id, request_id=self._rid()),
            user=user)
        return rsp.inode, rsp.session_id

    async def open(self, path: str, write: bool = False,
                   user: UserInfo | None = None,
                   rdwr: bool = False) -> tuple[Inode, str]:
        rsp = await self._call("open", PathReq(path=path, write=write,
                                               client_id=self.client_id,
                                               rdwr=rdwr),
                               user=user)
        return rsp.inode, rsp.session_id

    async def close(self, inode_id: int, session_id: str = "",
                    length: int = -1) -> Inode:
        return (await self._call("close", InodeReq(
            inode_id=inode_id, session_id=session_id, length=length))).inode

    async def sync(self, inode_id: int) -> Inode:
        return (await self._call("sync", InodeReq(inode_id=inode_id))).inode

    async def report_write_position(self, inode_id: int, position: int) -> None:
        await self._call("report_write_position",
                         InodeReq(inode_id=inode_id, position=position))

    async def mkdirs(self, path: str, perm: int = 0o755,
                     recursive: bool = True,
                     user: UserInfo | None = None) -> Inode:
        return (await self._call("mkdirs", PathReq(
            path=path, perm=perm, recursive=recursive,
            client_id=self.client_id, request_id=self._rid()),
            user=user)).inode

    async def readdir(self, path: str,
                      user: UserInfo | None = None) -> list[DirEntry]:
        return (await self._call("readdir", PathReq(path=path),
                                 user=user)).entries

    async def remove(self, path: str, recursive: bool = False,
                     user: UserInfo | None = None) -> None:
        await self._call("remove", PathReq(
            path=path, recursive=recursive, client_id=self.client_id,
            request_id=self._rid()), user=user)

    async def rename(self, src: str, dst: str, flags: int = 0,
                     user: UserInfo | None = None) -> None:
        # flags route to a separate method so an old server can never
        # mis-run a flagged rename as a plain destructive one
        await self._call("rename2" if flags else "rename", PathReq(
            path=src, target=dst, flags=flags, client_id=self.client_id,
            request_id=self._rid()), user=user)

    async def symlink(self, path: str, target: str,
                      user: UserInfo | None = None) -> Inode:
        return (await self._call("symlink", PathReq(
            path=path, target=target, client_id=self.client_id,
            request_id=self._rid()), user=user)).inode

    async def hardlink(self, existing: str, new_path: str,
                       user: UserInfo | None = None) -> Inode:
        return (await self._call("hardlink", PathReq(
            path=existing, target=new_path, client_id=self.client_id,
            request_id=self._rid()), user=user)).inode

    async def set_attr(self, path: str, perm: int,
                       user: UserInfo | None = None) -> Inode:
        return (await self._call("set_attr",
                                 PathReq(path=path, perm=perm),
                                 user=user)).inode

    async def truncate(self, inode_id: int, length: int,
                       user: UserInfo | None = None) -> Inode:
        return (await self._call("truncate", InodeReq(inode_id=inode_id,
                                                      length=length),
                                 user=user)).inode

    async def get_real_path(self, inode_id: int) -> str:
        return (await self._call("get_real_path", InodeReq(inode_id=inode_id))).path

    async def lookup(self, parent: int, name: str,
                     user: UserInfo | None = None) -> Inode:
        return (await self._call("lookup", EntryReq(
            parent=parent, name=name), user=user)).inode

    async def readdir_plus(self, inode_id: int, limit: int = 0,
                           user: UserInfo | None = None):
        """One-RPC listing: (dir inode, entries, entry inodes) from one
        snapshot — the FUSE OPENDIR hot path.  Falls back to the 3-RPC
        shape against an older meta server."""
        try:
            rsp = await self._call("readdir_plus",
                                   EntryReq(inode_id=inode_id, limit=limit),
                                   user=user)
            entries = [DirEntry(inode_id, n, i, InodeType(t))
                       for n, i, t in zip(rsp.names, rsp.ids, rsp.types)]
            return rsp.dir, entries, serde.loads_many(rsp.inode_blobs,
                                                      Inode)
        except StatusError as e:
            if e.code != StatusCode.RPC_METHOD_NOT_FOUND:
                raise
        entries = await self.readdir_inode(inode_id, limit, user=user)
        dir_inode = await self.stat_inode(inode_id)
        inodes = await self.batch_stat_inodes(
            [e.inode_id for e in entries]) if entries else []
        return dir_inode, entries, inodes

    async def readdir_inode(self, inode_id: int, limit: int = 0,
                            user: UserInfo | None = None
                            ) -> list[DirEntry]:
        return (await self._call("readdir_inode", EntryReq(
            inode_id=inode_id, limit=limit), user=user)).entries

    async def create_at(self, parent: int, name: str, perm: int = 0o644,
                        chunk_size: int = 0, stripe: int = 0,
                        write: bool = False,
                        user: UserInfo | None = None) -> tuple[Inode, str]:
        rsp = await self._call("create_at", EntryReq(
            parent=parent, name=name, perm=perm, chunk_size=chunk_size,
            stripe=stripe, write=write, client_id=self.client_id,
            request_id=self._rid()), user=user)
        return rsp.inode, rsp.session_id

    async def mkdir_at(self, parent: int, name: str, perm: int = 0o755,
                       user: UserInfo | None = None) -> Inode:
        return (await self._call("mkdir_at", EntryReq(
            parent=parent, name=name, perm=perm, client_id=self.client_id,
            request_id=self._rid()), user=user)).inode

    async def symlink_at(self, parent: int, name: str, target: str,
                         user: UserInfo | None = None) -> Inode:
        return (await self._call("symlink_at", EntryReq(
            parent=parent, name=name, target=target,
            client_id=self.client_id, request_id=self._rid()),
            user=user)).inode

    async def unlink_at(self, parent: int, name: str,
                        recursive: bool = False,
                        must_dir: bool | None = None,
                        user: UserInfo | None = None) -> None:
        await self._call("unlink_at", EntryReq(
            parent=parent, name=name, recursive=recursive,
            client_id=self.client_id, request_id=self._rid(),
            must_dir=-1 if must_dir is None else int(must_dir)),
            user=user)

    async def rename_at(self, sparent: int, sname: str, dparent: int,
                        dname: str, flags: int = 0,
                        user: UserInfo | None = None) -> None:
        """flags: renameat2(2) RENAME_NOREPLACE=1 / RENAME_EXCHANGE=2
        (flagged calls use their own method — see rename)."""
        await self._call("rename2_at" if flags else "rename_at", EntryReq(
            parent=sparent, name=sname, dparent=dparent, dname=dname,
            client_id=self.client_id, request_id=self._rid(),
            flags=flags), user=user)

    async def link_at(self, inode_id: int, parent: int, name: str,
                      user: UserInfo | None = None) -> Inode:
        return (await self._call("link_at", EntryReq(
            inode_id=inode_id, parent=parent, name=name,
            client_id=self.client_id, request_id=self._rid()),
            user=user)).inode

    async def open_inode(self, inode_id: int, write: bool = False,
                         user: UserInfo | None = None,
                         rdwr: bool = False) -> tuple[Inode, str]:
        rsp = await self._call("open_inode", EntryReq(
            inode_id=inode_id, write=write, client_id=self.client_id,
            rdwr=rdwr),
            user=user)
        return rsp.inode, rsp.session_id

    async def lock_directory(self, path: str, unlock: bool = False) -> Inode:
        return (await self._call("lock_directory", PathReq(
            path=path, client_id=self.client_id, unlock=unlock))).inode

    async def lock_directory_inode(self, inode_id: int,
                                   action: str) -> Inode:
        """try_lock | preempt_lock | unlock | clear on a directory nodeid
        (LockDirectory.cc:32-56); owner is this client's identity."""
        return (await self._call("lock_directory_inode", LockDirReq(
            inode_id=inode_id, client_id=self.client_id,
            action=action))).inode

    async def batch_stat(self, paths: list[str], follow: bool = True,
                         user: UserInfo | None = None
                         ) -> list[Inode | None]:
        return (await self._call("batch_stat", BatchStatReq(
            paths=paths, follow=follow), user=user)).inodes

    async def batch_stat_inodes(self, inode_ids: list[int]) -> list[Inode | None]:
        return (await self._call("batch_stat", BatchStatReq(
            inode_ids=inode_ids))).inodes

    async def set_attr_inode(self, inode_id: int, *, perm: int = -1,
                             uid: int = -1, gid: int = -1,
                             atime: float = -1.0,
                             mtime: float = -1.0,
                             user: UserInfo | None = None) -> Inode:
        """chmod/chown/utimens by nodeid (-1 = leave unchanged)."""
        return (await self._call("set_attr_inode", SetAttrReq(
            inode_id=inode_id, perm=perm, uid=uid, gid=gid,
            atime=atime, mtime=mtime), user=user)).inode

    async def prune_sessions(self, session_ids: list[str] = ()) -> None:
        """Release this client's write sessions eagerly (reference
        PruneSession): an unmounting daemon calls this instead of leaving
        its sessions to the dead-client reaper."""
        await self._call("prune_session", PruneSessionReq(
            client_id=self.client_id, session_ids=list(session_ids)))

    async def close_conn(self) -> None:
        await self.client.close()
