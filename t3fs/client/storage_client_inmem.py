"""StorageClientInMem: the whole storage client on a dict — used by meta and
FUSE tests to avoid storage entirely (reference:
client/storage/StorageClientInMem.cc, 395 LoC fake)."""

from __future__ import annotations

from dataclasses import dataclass, field

from t3fs.client.layout import FileLayout
from t3fs.net.wire import WireStatus
from t3fs.ops.codec import crc32c as crc32c_ref
from t3fs.storage.types import ChunkId, IOResult, ReadIO, UpdateType
from t3fs.utils.status import StatusCode


@dataclass
class _Chunk:
    data: bytes = b""
    update_ver: int = 0


class StorageClientInMem:
    """Duck-typed like StorageClient for the ops meta/FUSE need."""

    def __init__(self):
        self.chunks: dict[tuple[int, ChunkId], _Chunk] = {}

    async def write_chunk(self, chain_id: int, chunk_id: ChunkId, offset: int,
                          data: bytes, chunk_size: int,
                          update_type: UpdateType = UpdateType.WRITE,
                          truncate_len: int = 0,
                          checksum: int | None = None) -> IOResult:
        # checksum: accepted for StorageClient duck-type parity (EC repair
        # passes device-computed CRCs); the fake always re-CRCs itself.
        key = (chain_id, chunk_id)
        cur = self.chunks.get(key, _Chunk())
        if update_type == UpdateType.TRUNCATE:
            content = cur.data[:truncate_len].ljust(truncate_len, b"\x00")
        elif update_type == UpdateType.REMOVE:
            self.chunks.pop(key, None)
            return IOResult(WireStatus(), 0, cur.update_ver + 1, cur.update_ver + 1)
        else:
            end = offset + len(data)
            buf = bytearray(cur.data.ljust(max(len(cur.data), end), b"\x00"))
            buf[offset:end] = data
            content = bytes(buf)
        self.chunks[key] = _Chunk(content, cur.update_ver + 1)
        return IOResult(WireStatus(), len(content), cur.update_ver + 1,
                        cur.update_ver + 1, 1, crc32c_ref(content))

    async def batch_read(self, ios: list[ReadIO]):
        results, payloads = [], []
        for io in ios:
            chunk = self.chunks.get((io.chain_id, io.chunk_id))
            if chunk is None:
                results.append(IOResult(WireStatus(int(StatusCode.CHUNK_NOT_FOUND),
                                                   str(io.chunk_id))))
                payloads.append(b"")
                continue
            data = chunk.data[io.offset: io.offset + io.length
                              if io.length else len(chunk.data)]
            results.append(IOResult(WireStatus(), len(data), chunk.update_ver,
                                    chunk.update_ver, 1, crc32c_ref(chunk.data)))
            payloads.append(data)
        return results, payloads

    async def write_file_range(self, layout: FileLayout, inode: int,
                               offset: int, data: bytes) -> list[IOResult]:
        out = []
        pos = 0
        for idx, coff, span in layout.chunk_span(offset, len(data)):
            out.append(await self.write_chunk(
                layout.chain_of(idx), ChunkId(inode, idx), coff,
                data[pos: pos + span], layout.chunk_size))
            pos += span
        return out

    async def read_file_range(self, layout: FileLayout, inode: int,
                              offset: int, length: int):
        pieces = layout.chunk_span(offset, length)
        ios = [ReadIO(chunk_id=ChunkId(inode, idx), chain_id=layout.chain_of(idx),
                      offset=coff, length=span) for idx, coff, span in pieces]
        results, payloads = await self.batch_read(ios)
        data = bytearray()
        for (idx, coff, span), r, p in zip(pieces, results, payloads):
            data += p.ljust(span, b"\x00") if r.status.code in (
                int(StatusCode.OK), int(StatusCode.CHUNK_NOT_FOUND)) else p
        return bytes(data), results

    async def query_last_chunk(self, layout: FileLayout, inode: int) -> int:
        best = 0
        for (chain_id, cid), chunk in self.chunks.items():
            if cid.inode == inode:
                best = max(best, cid.index * layout.chunk_size + len(chunk.data))
        return best

    async def remove_file_chunks(self, layout: FileLayout, inode: int) -> None:
        for key in [k for k in self.chunks if k[1].inode == inode]:
            del self.chunks[key]

    async def truncate_file(self, layout: FileLayout, inode: int,
                            new_length: int) -> None:
        boundary = new_length // layout.chunk_size
        boundary_off = new_length - boundary * layout.chunk_size
        for key in list(self.chunks):
            if key[1].inode != inode:
                continue
            idx = key[1].index
            if idx > boundary or (idx == boundary and boundary_off == 0):
                del self.chunks[key]
            elif idx == boundary:
                c = self.chunks[key]
                self.chunks[key] = _Chunk(
                    c.data[:boundary_off].ljust(boundary_off, b"\x00"),
                    c.update_ver + 1)
        if boundary_off:
            # the real client TRUNCATE-writes the boundary chunk even when
            # it doesn't exist (exact-length semantics; the differential
            # fuzz caught the fake skipping this) — mirror it
            bkey = (layout.chain_of(boundary), ChunkId(inode, boundary))
            if bkey not in self.chunks:
                self.chunks[bkey] = _Chunk(b"\x00" * boundary_off, 1)

    async def close(self) -> None:
        pass
