"""RepairDriver: cluster-wide EC rebuild scheduling, balanced like the
placement solver plans it.

Reference analog: the BIBD placement solver balances *recovery traffic*
(deploy/data_placement/src/model/data_placement.py:30,484) — when a disk
dies, every chain that shared stripes with it sources survivor reads, and
the whole point of the balanced design is that no single surviving chain
becomes the rebuild bottleneck.  The reference's recovery is replica
resync; t3fs recovery is RS decode, so the driver must do what the solver
assumed: schedule stripe repairs so survivor-READ load stays even across
chains while rebuilt shards stream back to the recovered targets.

Scheduling: each stripe repair reads k survivor shards (one chain each)
and writes the lost shards.  The driver greedily orders pending stripes by
the current least-loaded-chain metric — at each step it picks the stripe
whose survivor set's maximum per-chain outstanding load is smallest, then
runs up to `concurrency` repairs with that ordering (an online version of
the solver's balance objective; exact assignment is the ILP the solver
already solved at placement time).
"""

from __future__ import annotations

import asyncio
import logging
from collections import defaultdict
from dataclasses import dataclass, field

from t3fs.client.ec_client import ECLayout, ECStorageClient
from t3fs.utils.status import StatusCode

log = logging.getLogger("t3fs.repair")


@dataclass
class RepairJob:
    """One file's losses: stripes -> lost shard indices."""
    layout: ECLayout
    inode: int
    stripe_len_of: dict[int, int]               # stripe -> true data length
    losses: dict[int, tuple[int, ...]] = field(default_factory=dict)


@dataclass
class RepairReport:
    repaired_stripes: int = 0
    repaired_shards: int = 0
    failed: list[tuple[int, int]] = field(default_factory=list)  # (inode, stripe)
    max_chain_reads: int = 0
    min_chain_reads: int = 0


class RepairDriver:
    """Schedules `ECStorageClient.repair_stripe` calls across many files,
    survivor-read-balanced."""

    def __init__(self, ec: ECStorageClient, concurrency: int = 8,
                 initial_load: dict[int, int] | None = None):
        self.ec = ec
        self.concurrency = concurrency
        # exact placement weights (mgmtd.placement.chain_recovery_weights):
        # chains the failure already loaded (resync sources, degraded-read
        # targets) start with their standing weight, so the survivor picks
        # steer around them instead of discovering the hotspot online
        self.initial_load = dict(initial_load or {})

    def plan(self, jobs: list[RepairJob]
             ) -> tuple[list[tuple["RepairJob", int, tuple[int, ...]]],
                        list[tuple[int, int]]]:
        """Choose, per stripe, WHICH k survivors to read and in what
        order, so survivor-read load stays flat across chains; returns
        (ordered [(job, stripe, chosen_shard_indices)], unrepairable
        [(inode, stripe)] — stripes with NO surviving shard).

        Decode needs exactly k of the k+m-|lost| survivors — reading all
        of them both wastes IO and concentrates load.  Each stripe takes
        the k survivors whose chains carry the least accumulated load
        (seeded from initial_load, the solver's exact weights).  Ordering
        uses a lazy-reevaluation heap: a popped entry whose score went
        stale is re-scored and re-pushed — O(P log P) typical instead of
        the naive O(P^2) scan, which would stall the event loop for
        minutes at cluster scale."""
        import heapq

        pending: list[tuple[RepairJob, int, list[tuple[int, int]]]] = []
        unrepairable: list[tuple[int, int]] = []
        for job in jobs:
            for stripe, lost in sorted(job.losses.items()):
                if not lost:
                    continue
                lay = job.layout
                lost_set = set(lost)
                survivors = [(s, lay.shard_chain(stripe, s))
                             for s in range(lay.k + lay.m)
                             if s not in lost_set]
                if not survivors:
                    unrepairable.append((job.inode, stripe))
                    continue
                pending.append((job, stripe, survivors))
        load: dict[int, int] = defaultdict(int, self.initial_load)

        def choose(entry) -> tuple[list[tuple[int, int]], int]:
            """k least-loaded survivors (all of them when fewer than k
            survive — the decode needs everything it can get) and the
            resulting score."""
            k = entry[0].layout.k
            ranked = sorted(entry[2], key=lambda sc: (load[sc[1]], sc[1]))
            chosen = ranked[:k]
            return chosen, max(load[c] for _s, c in chosen)

        heap = [(0, i) for i in range(len(pending))]
        heapq.heapify(heap)
        ordered: list[tuple[RepairJob, int, tuple[int, ...]]] = []
        while heap:
            s, i = heapq.heappop(heap)
            chosen, cur = choose(pending[i])
            if cur != s:
                heapq.heappush(heap, (cur, i))   # stale: re-score
                continue
            job, stripe, _survivors = pending[i]
            for _shard, c in chosen:
                load[c] += 1
            ordered.append((job, stripe,
                            tuple(shard for shard, _c in chosen)))
        return ordered, unrepairable

    async def run(self, jobs: list[RepairJob]) -> RepairReport:
        ordered, unrepairable = self.plan(jobs)
        report = RepairReport()
        report.failed.extend(unrepairable)
        for inode, stripe in unrepairable:
            log.warning("repair inode %d stripe %d: no surviving shards",
                        inode, stripe)
        # PLANNED survivor reads per chain (a failed preferred read falls
        # through to the patient wave and may touch other chains; zero-
        # hole shards substitute for free — the metric reflects the plan,
        # which is what the balancer controls).  Every candidate survivor
        # chain starts at 0 so a chain the picker left idle shows up in
        # min_chain_reads instead of being silently excluded.
        chain_reads: dict[int, int] = defaultdict(int)
        for job, stripe, _chosen in ordered:
            lost_set = set(job.losses[stripe])
            for s in range(job.layout.k + job.layout.m):
                if s not in lost_set:
                    chain_reads[job.layout.shard_chain(stripe, s)] += 0
        sem = asyncio.Semaphore(self.concurrency)

        async def one(job: RepairJob, stripe: int,
                      read_shards: tuple[int, ...]) -> None:
            lost = job.losses[stripe]
            async with sem:
                try:
                    results = await self.ec.repair_stripe(
                        job.layout, job.inode, stripe, lost,
                        stripe_len=job.stripe_len_of.get(
                            stripe, job.layout.k * job.layout.chunk_size),
                        read_shards=read_shards)
                except Exception as e:
                    log.warning("repair inode %d stripe %d failed: %s",
                                job.inode, stripe, e)
                    report.failed.append((job.inode, stripe))
                    return
                if all(r.status.code == int(StatusCode.OK)
                       for r in results):
                    report.repaired_stripes += 1
                    report.repaired_shards += len(lost)
                    for s in read_shards:    # the set the planner balanced
                        chain_reads[job.layout.shard_chain(stripe, s)] += 1
                else:
                    report.failed.append((job.inode, stripe))

        await asyncio.gather(*(one(j, s, sv) for j, s, sv in ordered))
        if chain_reads:
            report.max_chain_reads = max(chain_reads.values())
            report.min_chain_reads = min(chain_reads.values())
        return report
