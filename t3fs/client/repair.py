"""RepairDriver: cluster-wide EC rebuild scheduling, balanced like the
placement solver plans it.

Reference analog: the BIBD placement solver balances *recovery traffic*
(deploy/data_placement/src/model/data_placement.py:30,484) — when a disk
dies, every chain that shared stripes with it sources survivor reads, and
the whole point of the balanced design is that no single surviving chain
becomes the rebuild bottleneck.  The reference's recovery is replica
resync; t3fs recovery is RS decode, so the driver must do what the solver
assumed: schedule stripe repairs so survivor-READ load stays even across
chains while rebuilt shards stream back to the recovered targets.

Scheduling: each stripe repair reads k survivor shards (one chain each)
and writes the lost shards.  The driver greedily orders pending stripes by
the current least-loaded-chain metric — at each step it picks the stripe
whose survivor set's maximum per-chain outstanding load is smallest, then
runs up to `concurrency` repairs with that ordering (an online version of
the solver's balance objective; exact assignment is the ILP the solver
already solved at placement time).
"""

from __future__ import annotations

import asyncio
import logging
from collections import defaultdict
from dataclasses import dataclass, field

from t3fs.client.ec_client import ECLayout, ECStorageClient, RepairIOStats
from t3fs.utils.status import StatusCode

log = logging.getLogger("t3fs.repair")


class TokenBucketPacer:
    """Byte-rate token bucket for repair pacing (the _HedgeBudget shape, in
    bytes/s): acquire(nbytes) WAITS until the budget earns enough tokens —
    exhaustion is backpressure, never an error, so rebuild under a tight
    `storage.repair_budget_mbps` slows down instead of failing stripes.

    `burst_bytes` caps the idle accumulation (default one second of rate);
    `floor_bytes` is the minimum grant capacity, so a single request larger
    than the burst (one big stripe) clamps to the capacity and proceeds
    after draining it rather than deadlocking on tokens that can never
    accrue.  rate_mbps <= 0 disables pacing entirely."""

    def __init__(self, rate_mbps: float, burst_bytes: int | None = None,
                 floor_bytes: int = 1 << 20):
        self.rate = rate_mbps * 1e6                    # bytes per second
        self.capacity = max(int(burst_bytes if burst_bytes is not None
                                else self.rate), floor_bytes)
        self.tokens = float(self.capacity)
        self._last: float | None = None
        self._lock = asyncio.Lock()
        self.waits = 0
        self.waited_s = 0.0

    def _refill(self) -> None:
        import time
        now = time.monotonic()
        if self._last is not None:
            self.tokens = min(float(self.capacity),
                              self.tokens + (now - self._last) * self.rate)
        self._last = now

    async def acquire(self, nbytes: int) -> None:
        if self.rate <= 0:
            return
        take = float(min(nbytes, self.capacity))
        # serialized: FIFO fairness, and one sleeper computes exact deficit
        async with self._lock:
            self._refill()
            if self.tokens < take:
                wait = (take - self.tokens) / self.rate
                self.waits += 1
                self.waited_s += wait
                await asyncio.sleep(wait)
                self._refill()
            self.tokens -= take       # may dip below 0 on clock skew: debt


@dataclass
class RepairJob:
    """One file's losses: stripes -> lost shard indices."""
    layout: ECLayout
    inode: int
    stripe_len_of: dict[int, int]               # stripe -> true data length
    losses: dict[int, tuple[int, ...]] = field(default_factory=dict)


@dataclass
class RepairReport:
    repaired_stripes: int = 0
    repaired_shards: int = 0
    failed: list[tuple[int, int]] = field(default_factory=list)  # (inode, stripe)
    max_chain_reads: int = 0
    min_chain_reads: int = 0
    # IO accounting (ISSUE 9): what rebuilding cost the fabric.  The drill
    # metric is bytes_read / bytes_repaired — full-k repair pays ~k, the
    # reduced-read path ~group_size.
    bytes_read: int = 0
    bytes_repaired: int = 0
    stripes_failed: int = 0
    reduced_shards: int = 0
    fallback_shards: int = 0
    sub_reads: int = 0
    paced_waits: int = 0
    paced_wait_s: float = 0.0


class RepairDriver:
    """Schedules `ECStorageClient.repair_stripe` calls across many files,
    survivor-read-balanced; optionally paced by a byte-rate token bucket
    and routed down the reduced-read sub-shard path."""

    def __init__(self, ec: ECStorageClient, concurrency: int = 8,
                 initial_load: dict[int, int] | None = None,
                 repair_mode: str = "subshard",
                 budget_mbps: float = 0.0,
                 budget_burst_bytes: int | None = None):
        assert repair_mode in ("subshard", "full"), repair_mode
        self.ec = ec
        self.concurrency = concurrency
        self.repair_mode = repair_mode
        self.pacer = (TokenBucketPacer(budget_mbps, budget_burst_bytes)
                      if budget_mbps > 0 else None)
        # exact placement weights (mgmtd.placement.chain_recovery_weights):
        # chains the failure already loaded (resync sources, degraded-read
        # targets) start with their standing weight, so the survivor picks
        # steer around them instead of discovering the hotspot online
        self.initial_load = dict(initial_load or {})
        self._warmed: set[tuple] = set()

    async def warmup(self, layouts: list[ECLayout]) -> None:
        """Precompile each distinct layout's repair programs (off the event
        loop — compiles run on the codec thread) so the first repaired
        stripe doesn't eat the jit stall; run() calls this itself."""
        for lay in layouts:
            key = (lay.k, lay.m, lay.chunk_size, lay.code_id,
                   lay.local_scheme, lay.local_group_size)
            if key in self._warmed:
                continue
            self._warmed.add(key)
            await asyncio.to_thread(self.ec.warmup_repair, lay)

    def plan(self, jobs: list[RepairJob]
             ) -> tuple[list[tuple["RepairJob", int, tuple[int, ...]]],
                        list[tuple[int, int]]]:
        """Choose, per stripe, WHICH k survivors to read and in what
        order, so survivor-read load stays flat across chains; returns
        (ordered [(job, stripe, chosen_shard_indices)], unrepairable
        [(inode, stripe)] — stripes with NO surviving shard).

        Decode needs exactly k of the k+m-|lost| survivors — reading all
        of them both wastes IO and concentrates load.  Each stripe takes
        the k survivors whose chains carry the least accumulated load
        (seeded from initial_load, the solver's exact weights).  Ordering
        uses a lazy-reevaluation heap: a popped entry whose score went
        stale is re-scored and re-pushed — O(P log P) typical instead of
        the naive O(P^2) scan, which would stall the event loop for
        minutes at cluster scale."""
        import heapq

        pending: list[tuple[RepairJob, int, list[tuple[int, int]]]] = []
        unrepairable: list[tuple[int, int]] = []
        for job in jobs:
            for stripe, lost in sorted(job.losses.items()):
                if not lost:
                    continue
                lay = job.layout
                lost_set = set(lost)
                survivors = [(s, lay.shard_chain(stripe, s))
                             for s in range(lay.k + lay.m)
                             if s not in lost_set]
                if not survivors:
                    unrepairable.append((job.inode, stripe))
                    continue
                pending.append((job, stripe, survivors))
        load: dict[int, int] = defaultdict(int, self.initial_load)

        def choose(entry) -> tuple[list[tuple[int, int]], int]:
            """k least-loaded survivors (all of them when fewer than k
            survive — the decode needs everything it can get) and the
            resulting score."""
            k = entry[0].layout.k
            ranked = sorted(entry[2], key=lambda sc: (load[sc[1]], sc[1]))
            chosen = ranked[:k]
            return chosen, max(load[c] for _s, c in chosen)

        heap = [(0, i) for i in range(len(pending))]
        heapq.heapify(heap)
        ordered: list[tuple[RepairJob, int, tuple[int, ...]]] = []
        while heap:
            s, i = heapq.heappop(heap)
            chosen, cur = choose(pending[i])
            if cur != s:
                heapq.heappush(heap, (cur, i))   # stale: re-score
                continue
            job, stripe, _survivors = pending[i]
            for _shard, c in chosen:
                load[c] += 1
            ordered.append((job, stripe,
                            tuple(shard for shard, _c in chosen)))
        return ordered, unrepairable

    def _estimate_read_bytes(self, lay: ECLayout,
                             lost: tuple[int, ...]) -> int:
        """Pacing charge for one stripe: what its survivor reads should
        cost.  The bucket meters intent, so the estimate errs high (holes
        and short tails read fewer bytes than charged) — pacing must bound
        fabric load, not track it exactly."""
        cs = lay.chunk_size
        if self.repair_mode == "subshard" and lay.local_scheme == "pm-msr":
            from t3fs.ops.msr import default_msr
            code = default_msr(lay.k, lay.m)
            if len(lost) == 1:
                # every survivor ships its beta/alpha projection: d helpers
                # x beta sub-chunks = 0.5625x of k full chunks
                return code.d * code.beta * cs // code.alpha
            return lay.k * cs        # multi-loss: joint decode, exactly k
        if self.repair_mode == "subshard" and lay.local_scheme:
            groups = lay.local_groups()
            base = lay.k + lay.m
            return sum(
                len(groups[s - base if s >= base else lay.group_of(s)]) * cs
                for s in lost)
        return lay.k * cs

    async def run(self, jobs: list[RepairJob]) -> RepairReport:
        await self.warmup([j.layout for j in jobs])
        ordered, unrepairable = self.plan(jobs)
        stats = RepairIOStats()
        report = RepairReport()
        report.failed.extend(unrepairable)
        for inode, stripe in unrepairable:
            log.warning("repair inode %d stripe %d: no surviving shards",
                        inode, stripe)
        # PLANNED survivor reads per chain (a failed preferred read falls
        # through to the patient wave and may touch other chains; zero-
        # hole shards substitute for free — the metric reflects the plan,
        # which is what the balancer controls).  Every candidate survivor
        # chain starts at 0 so a chain the picker left idle shows up in
        # min_chain_reads instead of being silently excluded.
        chain_reads: dict[int, int] = defaultdict(int)
        for job, stripe, _chosen in ordered:
            lost_set = set(job.losses[stripe])
            for s in range(job.layout.k + job.layout.m):
                if s not in lost_set:
                    chain_reads[job.layout.shard_chain(stripe, s)] += 0
        sem = asyncio.Semaphore(self.concurrency)

        async def one(job: RepairJob, stripe: int,
                      read_shards: tuple[int, ...]) -> None:
            lost = job.losses[stripe]
            async with sem:
                if self.pacer is not None:
                    await self.pacer.acquire(
                        self._estimate_read_bytes(job.layout, lost))
                try:
                    results = await self.ec.repair_stripe(
                        job.layout, job.inode, stripe, lost,
                        stripe_len=job.stripe_len_of.get(
                            stripe, job.layout.k * job.layout.chunk_size),
                        read_shards=read_shards, mode=self.repair_mode,
                        stats=stats)
                except Exception as e:
                    log.warning("repair inode %d stripe %d failed: %s",
                                job.inode, stripe, e)
                    report.failed.append((job.inode, stripe))
                    return
                if all(r.status.code == int(StatusCode.OK)
                       for r in results):
                    report.repaired_stripes += 1
                    report.repaired_shards += len(lost)
                    for s in read_shards:    # the set the planner balanced
                        chain_reads[job.layout.shard_chain(stripe, s)] += 1
                else:
                    report.failed.append((job.inode, stripe))

        await asyncio.gather(*(one(j, s, sv) for j, s, sv in ordered))
        if chain_reads:
            report.max_chain_reads = max(chain_reads.values())
            report.min_chain_reads = min(chain_reads.values())
        report.bytes_read = stats.bytes_read
        report.bytes_repaired = stats.bytes_repaired
        report.reduced_shards = stats.reduced_shards
        report.fallback_shards = stats.fallback_shards
        report.sub_reads = stats.sub_reads
        report.stripes_failed = len(report.failed)
        if self.pacer is not None:
            report.paced_waits = self.pacer.waits
            report.paced_wait_s = self.pacer.waited_s
        return report
