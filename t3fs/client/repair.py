"""RepairDriver: cluster-wide EC rebuild scheduling, balanced like the
placement solver plans it.

Reference analog: the BIBD placement solver balances *recovery traffic*
(deploy/data_placement/src/model/data_placement.py:30,484) — when a disk
dies, every chain that shared stripes with it sources survivor reads, and
the whole point of the balanced design is that no single surviving chain
becomes the rebuild bottleneck.  The reference's recovery is replica
resync; t3fs recovery is RS decode, so the driver must do what the solver
assumed: schedule stripe repairs so survivor-READ load stays even across
chains while rebuilt shards stream back to the recovered targets.

Scheduling: each stripe repair reads k survivor shards (one chain each)
and writes the lost shards.  The driver greedily orders pending stripes by
the current least-loaded-chain metric — at each step it picks the stripe
whose survivor set's maximum per-chain outstanding load is smallest, then
runs up to `concurrency` repairs with that ordering (an online version of
the solver's balance objective; exact assignment is the ILP the solver
already solved at placement time).
"""

from __future__ import annotations

import asyncio
import logging
from collections import defaultdict
from dataclasses import dataclass, field

from t3fs.client.ec_client import ECLayout, ECStorageClient
from t3fs.utils.status import StatusCode

log = logging.getLogger("t3fs.repair")


@dataclass
class RepairJob:
    """One file's losses: stripes -> lost shard indices."""
    layout: ECLayout
    inode: int
    stripe_len_of: dict[int, int]               # stripe -> true data length
    losses: dict[int, tuple[int, ...]] = field(default_factory=dict)


@dataclass
class RepairReport:
    repaired_stripes: int = 0
    repaired_shards: int = 0
    failed: list[tuple[int, int]] = field(default_factory=list)  # (inode, stripe)
    max_chain_reads: int = 0
    min_chain_reads: int = 0


class RepairDriver:
    """Schedules `ECStorageClient.repair_stripe` calls across many files,
    survivor-read-balanced."""

    def __init__(self, ec: ECStorageClient, concurrency: int = 8):
        self.ec = ec
        self.concurrency = concurrency

    @staticmethod
    def plan(jobs: list[RepairJob]
             ) -> tuple[list[tuple[RepairJob, int, list[int]]],
                        list[tuple[int, int]]]:
        """Order stripes so survivor reads spread evenly; returns
        (ordered [(job, stripe, survivor_chains)], unrepairable
        [(inode, stripe)] — stripes with NO surviving shard).

        Greedy with a lazy-reevaluation heap: pop the stripe whose
        survivor chains carry the least accumulated load (score = max
        per-chain counter); a popped entry whose score went stale since
        push is re-scored and re-pushed — O(P log P) typical instead of
        the naive O(P^2) scan, which would stall the event loop for
        minutes at cluster scale."""
        import heapq

        pending: list[tuple[RepairJob, int, list[int]]] = []
        unrepairable: list[tuple[int, int]] = []
        for job in jobs:
            for stripe, lost in sorted(job.losses.items()):
                if not lost:
                    continue
                lay = job.layout
                lost_set = set(lost)
                # _reconstruct_shards fetches EVERY survivor (decode picks
                # k of them); read load lands on all of their chains
                survivors = [lay.shard_chain(stripe, s)
                             for s in range(lay.k + lay.m)
                             if s not in lost_set]
                if not survivors:
                    unrepairable.append((job.inode, stripe))
                    continue
                pending.append((job, stripe, survivors))
        load: dict[int, int] = defaultdict(int)

        def score(entry) -> int:
            return max(load[c] for c in entry[2])

        heap = [(0, i) for i in range(len(pending))]
        heapq.heapify(heap)
        ordered: list[tuple[RepairJob, int, list[int]]] = []
        while heap:
            s, i = heapq.heappop(heap)
            cur = score(pending[i])
            if cur != s:
                heapq.heappush(heap, (cur, i))   # stale: re-score
                continue
            entry = pending[i]
            for c in entry[2]:
                load[c] += 1
            ordered.append(entry)
        return ordered, unrepairable

    async def run(self, jobs: list[RepairJob]) -> RepairReport:
        ordered, unrepairable = self.plan(jobs)
        report = RepairReport()
        report.failed.extend(unrepairable)
        for inode, stripe in unrepairable:
            log.warning("repair inode %d stripe %d: no surviving shards",
                        inode, stripe)
        chain_reads: dict[int, int] = defaultdict(int)
        sem = asyncio.Semaphore(self.concurrency)

        async def one(job: RepairJob, stripe: int,
                      survivors: list[int]) -> None:
            lost = job.losses[stripe]
            async with sem:
                try:
                    results = await self.ec.repair_stripe(
                        job.layout, job.inode, stripe, lost,
                        stripe_len=job.stripe_len_of.get(
                            stripe, job.layout.k * job.layout.chunk_size))
                except Exception as e:
                    log.warning("repair inode %d stripe %d failed: %s",
                                job.inode, stripe, e)
                    report.failed.append((job.inode, stripe))
                    return
                if all(r.status.code == int(StatusCode.OK)
                       for r in results):
                    report.repaired_stripes += 1
                    report.repaired_shards += len(lost)
                    for c in survivors:      # the set the planner balanced
                        chain_reads[c] += 1
                else:
                    report.failed.append((job.inode, stripe))

        await asyncio.gather(*(one(j, s, sv) for j, s, sv in ordered))
        if chain_reads:
            report.max_chain_reads = max(chain_reads.values())
            report.min_chain_reads = min(chain_reads.values())
        return report
