"""Rendezvous-hashed chain tables: elastic placement for CR and EC chains.

Reference analog: deploy/data_placement -type {CR,EC} — the reference
solves placement as an integer program per *epoch*; when membership
changes it re-solves from scratch and the new table can move almost
every chain.  t3fs instead derives the table from highest-random-weight
(rendezvous) hashing so membership change is *incremental by
construction*:

  score(chain, node) = mix64(chain_id, node_id)   # stable, uniform
  owners(chain)      = top-R nodes by score, one per failure domain

Removing a node only reassigns the chains where it was a top-R owner
(expected chains*R/N); every other chain's owner set is bit-identical.
Adding a node only steals the chains where it now ranks top-R.  A
bounded *capacity pass* then repairs statistical imbalance: nodes over
``ceil(chains*R/N) + cap_slack`` demote their lowest-score wins to the
best under-cap runner-up, so the table stays balanced without an ILP
while churn stays local.

Failure domains come from node tags (``domain:rackA``); untagged nodes
are their own domain.  EC tables are the R=1 case (single-replica shard
chains), CR tables R=replicas — same math, matching the reference's two
table types.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass, field

from t3fs.mgmtd.types import ChainInfo, NodeInfo, RoutingInfo

DOMAIN_TAG_PREFIX = "domain:"


def _mix64(x: int) -> int:
    """splitmix64 finalizer: deterministic across processes/runs (unlike
    Python's salted hash()) — the table must be reproducible everywhere."""
    x = (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)


def rendezvous_score(chain_id: int, node_id: int, salt: int = 0) -> int:
    """Stable per-(chain, node) weight; the whole table derives from it."""
    return _mix64((chain_id << 24) ^ (node_id << 4) ^ salt)


def node_domain(node: NodeInfo) -> str:
    """Failure domain from operator tags; untagged = its own domain."""
    for t in node.tags or ():
        if isinstance(t, str) and t.startswith(DOMAIN_TAG_PREFIX):
            return t[len(DOMAIN_TAG_PREFIX):]
    return f"node:{node.node_id}"


@dataclass
class SolvedTable:
    """Target assignment for one chain table."""
    table_type: str                               # "cr" | "ec"
    replicas: int
    assignment: dict[int, list[int]] = field(default_factory=dict)
    # chains whose owner set the capacity pass changed vs pure HRW
    # (observability: how much balance cost in churn)
    capacity_moves: int = 0

    def nodes_of(self, chain_id: int) -> list[int]:
        return self.assignment.get(chain_id, [])


def solve_chain_table(chain_ids: list[int], nodes: list[NodeInfo],
                      replicas: int, *, table_type: str = "cr",
                      cap_slack: int = 1, salt: int = 0) -> SolvedTable:
    """Rendezvous-derive the owner set of every chain, then repair
    imbalance with a bounded capacity pass.

    ``cap_slack`` trades balance for churn: 0 forces the tightest
    per-node load (more movement on membership change), larger values
    keep more pure-HRW wins (less movement, looser balance)."""
    if table_type == "ec":
        replicas = 1
    if replicas < 1:
        raise ValueError(f"replicas {replicas} < 1")
    if len(nodes) < replicas:
        raise ValueError(
            f"{len(nodes)} nodes < {replicas} replicas: cannot place")
    domains = {n.node_id: node_domain(n) for n in nodes}
    distinct_domains = len(set(domains.values()))
    solved = SolvedTable(table_type=table_type, replicas=replicas)

    # pass 1: pure HRW owner sets, one node per failure domain when the
    # topology has enough domains (else the constraint is vacuous and
    # dropped — a 3-node rack must still be placeable)
    want_domains = distinct_domains >= replicas
    ranked: dict[int, list[int]] = {}
    for cid in chain_ids:
        order = sorted((n.node_id for n in nodes),
                       key=lambda nid: rendezvous_score(cid, nid, salt),
                       reverse=True)
        ranked[cid] = order
        owners: list[int] = []
        used_domains: set[str] = set()
        for nid in order:
            if want_domains and domains[nid] in used_domains:
                continue
            owners.append(nid)
            used_domains.add(domains[nid])
            if len(owners) == replicas:
                break
        if len(owners) < replicas:       # domain filter too strict: relax
            for nid in order:
                if nid not in owners:
                    owners.append(nid)
                    if len(owners) == replicas:
                        break
        solved.assignment[cid] = owners

    # pass 2: capacity repair.  Overloaded nodes demote their LOWEST-
    # score wins (the ones a membership change would most likely move
    # anyway) to the best-scored under-cap candidate not already on the
    # chain.  Processing one demotion at a time keeps the pass greedy
    # and the churn bounded by the overload itself.
    total = len(chain_ids) * replicas
    cap = -(-total // max(1, len(nodes))) + max(0, cap_slack)
    load: dict[int, int] = {n.node_id: 0 for n in nodes}
    for owners in solved.assignment.values():
        for nid in owners:
            load[nid] += 1
    over = [nid for nid, c in load.items() if c > cap]
    for nid in over:
        # wins sorted ascending by score: shed the weakest claims first
        wins = sorted(
            (cid for cid, owners in solved.assignment.items()
             if nid in owners),
            key=lambda cid: rendezvous_score(cid, nid, salt))
        for cid in wins:
            if load[nid] <= cap:
                break
            owners = solved.assignment[cid]
            used = {domains[o] for o in owners if o != nid}
            for cand in ranked[cid]:
                if cand in owners or load[cand] >= cap:
                    continue
                if want_domains and domains[cand] in used:
                    continue
                owners[owners.index(nid)] = cand
                load[nid] -= 1
                load[cand] += 1
                solved.capacity_moves += 1
                break
    return solved


def solve_for_routing(routing: RoutingInfo, table_id: int,
                      nodes: list[NodeInfo], *, replicas: int | None = None,
                      cap_slack: int = 1) -> SolvedTable:
    """Solve one existing chain table against a candidate node set.
    Table 1 is CR, any other table is EC (single-replica shard chains).

    CR replication comes from the table's persisted ``replicas`` when
    set; the fallback for pre-15 tables uses the MODE of live chain
    widths, never the max — a chain mid-migration transiently carries
    R+1 targets (dst joined, src not yet detached), and solving for the
    inflated max would pair a second destination onto that chain and
    ratchet the whole table to R+1 on every subsequent solve."""
    table = routing.chain_tables.get(table_id)
    if table is None:
        raise ValueError(f"chain table {table_id} not in routing")
    table_type = getattr(table, "table_type", "") or \
        ("cr" if table_id == 1 else "ec")
    if replicas is None:
        if table_type != "cr":
            replicas = 1
        elif getattr(table, "replicas", 0) > 0:
            replicas = table.replicas
        else:
            widths = Counter(
                len(c.targets) for cid in table.chain_ids
                if (c := routing.chain(cid)) is not None)
            if not widths:
                replicas = 1
            else:
                top = max(widths.values())
                replicas = min(w for w, n in widths.items() if n == top)
    return solve_chain_table(list(table.chain_ids), nodes, replicas,
                             table_type=table_type, cap_slack=cap_slack)


@dataclass
class ChainMove:
    """One planned membership change: src target leaves, dst node joins."""
    chain_id: int = 0
    src_target_id: int = 0
    src_node_id: int = 0
    dst_node_id: int = 0
    dst_target_id: int = 0


def diff_table(routing: RoutingInfo, solved: SolvedTable,
               *, target_id_of=None) -> list[ChainMove]:
    """Per-chain moves from the CURRENT membership to the solved target.
    Pairs leaving nodes with joining nodes deterministically (sorted).
    Surplus leaves beyond the joins (an over-wide chain, e.g. R+1 left
    behind by an interrupted move whose JOIN applied but whose DETACH
    never ran) become *shrink* moves: the src is paired with a retained
    member already on the chain, so the migration driver sees the dst
    SERVING and skips straight to DRAIN+DETACH of the surplus target —
    without this the planner can never walk an over-wide chain back to
    R and the table wedges un-converged.  A chain that only GROWS is
    still not a move (that is repair's job, not the rebalancer's)."""
    from t3fs.mgmtd.placement import target_id as _tid
    target_id_of = target_id_of or _tid
    moves: list[ChainMove] = []
    for cid in sorted(solved.assignment):
        chain = routing.chain(cid)
        if chain is None:
            continue
        current = {t.node_id: t.target_id for t in chain.targets}
        want = set(solved.assignment[cid])
        leave = sorted(n for n in current if n not in want)
        join = sorted(n for n in want if n not in current)
        for src_node, dst_node in zip(leave, join):
            moves.append(ChainMove(
                chain_id=cid,
                src_target_id=current[src_node], src_node_id=src_node,
                dst_node_id=dst_node,
                dst_target_id=target_id_of(dst_node, cid - 1)))
        keep = sorted(n for n in want if n in current)
        if keep:
            for src_node in leave[len(join):]:
                moves.append(ChainMove(
                    chain_id=cid,
                    src_target_id=current[src_node], src_node_id=src_node,
                    dst_node_id=keep[0],
                    # the retained member's EXISTING target: the driver
                    # finds it SERVING and goes straight to DRAIN
                    dst_target_id=current[keep[0]]))
    return moves


def reassigned_chains(before: SolvedTable, after: SolvedTable) -> list[int]:
    """Chains whose owner set changed between two solves (test/ops
    helper for the minimal-movement property)."""
    out = []
    for cid, owners in before.assignment.items():
        if sorted(after.assignment.get(cid, [])) != sorted(owners):
            out.append(cid)
    return out
