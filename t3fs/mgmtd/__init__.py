"""Cluster manager: routing info, chains, heartbeat/lease, chain state
machine (reference: src/mgmtd/ — SURVEY.md §2.4)."""
