"""Cluster/routing data types.

Reference analogs: fbs/mgmtd/MgmtdTypes.h (PublicTargetState :10,
LocalTargetState :21, strong-typedef ids :55), ChainInfo/ChainTable,
RoutingInfo (fbs/mgmtd/RoutingInfo.h:11-46), HeartbeatInfo.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from t3fs.utils.serde import serde_struct


class PublicTargetState(enum.IntEnum):
    """Target state as published in the chain (MgmtdTypes.h:10)."""
    INVALID = 0
    SERVING = 1       # full chain member, serves reads+writes
    SYNCING = 2       # being brought up to date by predecessor
    WAITING = 3       # offline target waiting to re-join (at chain tail)
    LASTSRV = 4       # last serving target that went offline (still authoritative)
    OFFLINE = 5


class LocalTargetState(enum.IntEnum):
    """Target state as reported by its node in heartbeats (MgmtdTypes.h:21)."""
    INVALID = 0
    UPTODATE = 1
    ONLINE = 2
    OFFLINE = 3


class NodeStatus(enum.IntEnum):
    ACTIVE = 1
    FAILED = 2
    DISABLED = 3


@serde_struct
@dataclass
class ChainTargetInfo:
    target_id: int = 0
    node_id: int = 0
    public_state: PublicTargetState = PublicTargetState.SERVING


@serde_struct
@dataclass
class ChainInfo:
    chain_id: int = 0
    chain_ver: int = 1
    targets: list[ChainTargetInfo] = field(default_factory=list)
    # targets are in chain order: head first; only SERVING targets form the
    # live chain, SYNCING follow, WAITING/OFFLINE tail out (design_notes 201-231)
    # operator-preferred target order (fbs/mgmtd ChainInfo.preferredTargetOrder):
    # rotate_as_preferred_order nudges the chain back toward it one resync
    # cycle at a time; empty = no preference
    preferred_target_order: list[int] = field(default_factory=list)

    def serving(self) -> list[ChainTargetInfo]:
        return [t for t in self.targets if t.public_state == PublicTargetState.SERVING]

    def syncing(self) -> list[ChainTargetInfo]:
        return [t for t in self.targets if t.public_state == PublicTargetState.SYNCING]

    def head(self) -> ChainTargetInfo | None:
        s = self.serving()
        return s[0] if s else None

    def tail(self) -> ChainTargetInfo | None:
        s = self.serving()
        return s[-1] if s else None

    def successor_of(self, target_id: int) -> ChainTargetInfo | None:
        """Next live participant after target_id (serving chain + syncing tail)."""
        live = self.serving() + self.syncing()
        for i, t in enumerate(live):
            if t.target_id == target_id:
                return live[i + 1] if i + 1 < len(live) else None
        return None


@serde_struct
@dataclass
class NodeInfo:
    node_id: int = 0
    address: str = ""            # host:port of the storage/meta service
    node_type: str = "storage"   # storage | meta | mgmtd
    status: NodeStatus = NodeStatus.ACTIVE
    # process generation (start timestamp): lets mgmtd detect a crash-restart
    # that happened WITHIN the heartbeat window — the node looks continuously
    # alive but its serving targets may have lost state and need resync
    generation: float = 0.0
    # operator labels (setNodeTags; placement/ops tooling reads these)
    tags: list = field(default_factory=list)


@serde_struct
@dataclass
class ChainTable:
    """Ordered list of chain ids used for striping layouts
    (fbs/mgmtd/ChainTable.h analog).

    table_ver bumps on every re-install (ISSUE 15: clients compare it to
    decide whether a table's membership solve moved under them without
    re-reading every chain); table_type mirrors the reference solver's
    -type {CR,EC} split — "cr" replicated chains, "ec" single-replica
    shard chains; replicas persists the DESIRED replication so the
    solver never has to infer it from live chain widths (which are
    transiently R+1 mid-migration).  All serde add-only: pre-15 peers
    leave defaults (replicas=0 = unknown, solver falls back to widths)."""
    table_id: int = 1
    chain_ids: list[int] = field(default_factory=list)
    table_ver: int = 1
    table_type: str = ""
    replicas: int = 0


@serde_struct
@dataclass
class ClientSession:
    """A registered client (FUSE daemon, bench, library user) with a lease
    the MgmtdClientSessionsChecker analog prunes (fbs/mgmtd/ClientSession.h:12,
    mgmtd/background/MgmtdClientSessionsChecker.h)."""
    client_id: str = ""
    universal_id: str = ""       # host identity (survives client restart)
    description: str = ""
    start: float = 0.0
    last_extend: float = 0.0


@serde_struct
@dataclass
class RoutingInfo:
    """The cluster map every client/server caches (RoutingInfo.h:11-46)."""
    version: int = 1
    bootstrapping: bool = False
    nodes: dict[int, NodeInfo] = field(default_factory=dict)
    chains: dict[int, ChainInfo] = field(default_factory=dict)
    chain_tables: dict[int, ChainTable] = field(default_factory=dict)

    def chain(self, chain_id: int) -> ChainInfo | None:
        return self.chains.get(chain_id)

    def node_address(self, node_id: int) -> str | None:
        n = self.nodes.get(node_id)
        return n.address if n else None
