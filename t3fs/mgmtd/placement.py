"""Data placement: choose chain groups under failure-domain constraints.

Reference analog: deploy/data_placement/ (Pyomo+HiGHS integer program
balancing recovery traffic, -type {CR,EC}).  t3fs v1 ships the load-bearing
property as a greedy solver: an EC(k+m) stripe survives a node failure only
if no node hosts more than m of its shards — the TPU decode probe on a
3-node/10-chain topology demonstrated exactly this failure mode.
"""

from __future__ import annotations

from collections import Counter

from t3fs.mgmtd.types import RoutingInfo


def target_id(node_id: int, chain_idx: int) -> int:
    """Canonical dev/test target-id scheme shared by the cluster launchers
    and admin gen-chains: one target per (node, chain slot)."""
    return node_id * 100 + chain_idx + 1


def chain_nodes(routing: RoutingInfo, chain_id: int) -> list[int]:
    chain = routing.chain(chain_id)
    return [t.node_id for t in chain.targets] if chain else []


def select_ec_chains(routing: RoutingInfo, k: int, m: int,
                     candidates: list[int] | None = None) -> list[int]:
    """Greedily pick k+m chains such that no node appears on more than m of
    them (single-node loss then costs <= m shards = decodable).

    Greedy, not exhaustive: prefers chains with fewer targets so wide
    (multi-replica) chains don't block narrow ones; a ValueError means THIS
    heuristic failed — a different candidate ordering or the full integer
    program (reference deploy/data_placement) may still find a placement."""
    want = k + m
    cands = candidates if candidates is not None else sorted(routing.chains)
    cands = sorted(cands, key=lambda c: len(chain_nodes(routing, c)))
    chosen: list[int] = []
    node_load: Counter = Counter()
    for cid in cands:
        nodes = chain_nodes(routing, cid)
        if not nodes:
            continue
        if any(node_load[n] + 1 > m for n in nodes):
            continue
        chosen.append(cid)
        node_load.update(nodes)
        if len(chosen) == want:
            return chosen
    raise ValueError(
        f"greedy EC({k}+{m}) placement failed: {len(chosen)} of {want} "
        f"chains selected before node budgets ({m} shards each) were "
        f"exhausted — add nodes/chains or try explicit candidates")


def validate_ec_chains(routing: RoutingInfo, chains: list[int], m: int) -> bool:
    """True iff no single node hosts more than m of these chains' targets."""
    node_load: Counter = Counter()
    for cid in chains:
        node_load.update(chain_nodes(routing, cid))
    return all(c <= m for c in node_load.values())
