"""Data placement: choose chain groups under failure-domain constraints.

Reference analog: deploy/data_placement/ (Pyomo+HiGHS integer program
balancing recovery traffic, -type {CR,EC}).  t3fs v1 ships the load-bearing
property as a greedy solver: an EC(k+m) stripe survives a node failure only
if no node hosts more than m of its shards — the TPU decode probe on a
3-node/10-chain topology demonstrated exactly this failure mode.
"""

from __future__ import annotations

from collections import Counter

from t3fs.mgmtd.types import RoutingInfo


def target_id(node_id: int, chain_idx: int) -> int:
    """Canonical dev/test target-id scheme shared by the cluster launchers
    and admin gen-chains: one target per (node, chain slot)."""
    return node_id * 100 + chain_idx + 1


def chain_nodes(routing: RoutingInfo, chain_id: int) -> list[int]:
    chain = routing.chain(chain_id)
    return [t.node_id for t in chain.targets] if chain else []


def select_ec_chains(routing: RoutingInfo, k: int, m: int,
                     candidates: list[int] | None = None) -> list[int]:
    """Pick k+m chains such that no node appears on more than m of them
    (single-node loss then costs <= m shards = decodable).

    Solve-then-validate (ISSUE 15): the greedy pass (prefer chains with
    fewer targets so wide multi-replica chains don't block narrow ones)
    is tried first; when IT fails, a swap local search repairs the
    selection instead of giving up — greedy failure is an ordering
    artifact, not infeasibility.  The result is always checked with
    validate_ec_chains before it is returned; ValueError now means the
    search exhausted its effort, not that one heuristic ordering lost."""
    want = k + m
    cands = candidates if candidates is not None else sorted(routing.chains)
    cands = sorted(cands, key=lambda c: len(chain_nodes(routing, c)))
    cands = [c for c in cands if chain_nodes(routing, c)]
    chosen: list[int] = []
    node_load: Counter = Counter()
    for cid in cands:
        nodes = chain_nodes(routing, cid)
        if any(node_load[n] + 1 > m for n in nodes):
            continue
        chosen.append(cid)
        node_load.update(nodes)
        if len(chosen) == want:
            return chosen
    repaired = _repair_ec_selection(routing, cands, want, m)
    if repaired is not None and validate_ec_chains(routing, repaired, m):
        return repaired
    raise ValueError(
        f"EC({k}+{m}) placement failed: greedy reached {len(chosen)} of "
        f"{want} chains and swap repair found no valid selection among "
        f"{len(cands)} candidates — add nodes/chains or relax m")


def _repair_ec_selection(routing: RoutingInfo, cands: list[int],
                         want: int, m: int,
                         max_steps: int = 400) -> list[int] | None:
    """Swap local search over chain selections: minimize the total
    per-node overload sum(max(0, load - m)).  Starts from the first
    `want` candidates, repeatedly swaps one selected chain for one
    unselected chain whenever that strictly reduces overload; 0 overload
    is exactly the validate_ec_chains invariant."""
    if len(cands) < want:
        return None
    selected = list(cands[:want])
    rest = [c for c in cands if c not in selected]
    load: Counter = Counter()
    for cid in selected:
        load.update(chain_nodes(routing, cid))

    def overload(cnt: Counter) -> int:
        return sum(v - m for v in cnt.values() if v > m)

    cur = overload(load)
    for _ in range(max_steps):
        if cur == 0:
            return selected
        best = (0, None, None)
        for i, out_c in enumerate(selected):
            out_nodes = chain_nodes(routing, out_c)
            for j, in_c in enumerate(rest):
                trial = Counter(load)
                trial.subtract(out_nodes)
                trial.update(chain_nodes(routing, in_c))
                d = overload(trial) - cur
                if d < best[0]:
                    best = (d, i, j)
        d, i, j = best
        if i is None:
            return None                  # local minimum with overload left
        out_c, in_c = selected[i], rest[j]
        load.subtract(chain_nodes(routing, out_c))
        load.update(chain_nodes(routing, in_c))
        selected[i], rest[j] = in_c, out_c
        cur += d
    return selected if cur == 0 else None


def validate_ec_chains(routing: RoutingInfo, chains: list[int], m: int) -> bool:
    """True iff no single node hosts more than m of these chains' targets."""
    node_load: Counter = Counter()
    for cid in chains:
        node_load.update(chain_nodes(routing, cid))
    return all(c <= m for c in node_load.values())


# --- recovery-traffic-balanced chain-table construction ----------------------
#
# Reference analog: deploy/data_placement/src/model/data_placement.py:30,
# 484-490 — a Pyomo+HiGHS integer program whose objective approximates a
# balanced incomplete block design: every PAIR of nodes should co-occur on
# (nearly) the same number of chains.  Why pairs: when node f fails, each
# chain through f is recovered by reads from that chain's OTHER members, so
# node j's share of f's recovery traffic is pair_count(f, j).  A balanced
# pair matrix spreads reconstruction load evenly and minimizes recovery
# time.  t3fs solves the same objective with greedy-swap local search
# (sum-of-squares of pair counts), which reaches the integer optimum's
# neighborhood for practical topologies without an ILP dependency.


def pair_counts(assignment: list[list[int]], num_nodes: int) -> Counter:
    """(i, j) i<j -> number of chains containing both nodes."""
    pc: Counter = Counter()
    for nodes in assignment:
        s = sorted(set(nodes))
        for a in range(len(s)):
            for b in range(a + 1, len(s)):
                pc[(s[a], s[b])] += 1
    return pc


def recovery_load(assignment: list[list[int]], num_nodes: int,
                  failed: int) -> Counter:
    """node -> chains it co-hosts with `failed` (its recovery read share)."""
    load: Counter = Counter()
    for nodes in assignment:
        if failed in nodes:
            for n in nodes:
                if n != failed:
                    load[n] += 1
    return load


def _ss(pc: Counter) -> int:
    return sum(v * v for v in pc.values())


def build_chain_table(num_nodes: int, num_chains: int, replicas: int,
                      *, sweeps: int = 60, seed: int = 0) -> list[list[int]]:
    """Assign `replicas` distinct nodes (1-based ids) to each chain with
    per-node chain counts balanced and pairwise co-occurrence as flat as the
    integer constraints allow (the BIBD objective).

    Greedy-swap local search: start from the round-robin table, then
    repeatedly replace one member of one chain with an underloaded/
    pair-reducing node whenever that strictly lowers the sum of squared pair
    counts while keeping per-node chain counts within the balanced band."""
    import random as _random

    assert 1 <= replicas <= num_nodes
    rng = _random.Random(seed)
    nodes = list(range(1, num_nodes + 1))
    assignment = [[nodes[(c + r) % num_nodes] for r in range(replicas)]
                  for c in range(num_chains)]
    total = num_chains * replicas
    cap_lo, cap_hi = total // num_nodes, -(-total // num_nodes)
    per_node: Counter = Counter(n for ch in assignment for n in ch)
    pc = pair_counts(assignment, num_nodes)

    def swap_delta(chain: list[int], out_n: int, in_n: int) -> int:
        """Change in sum-of-squares if out_n -> in_n within this chain."""
        delta = 0
        for other in chain:
            if other in (out_n, in_n):
                continue
            ko = tuple(sorted((out_n, other)))
            ki = tuple(sorted((in_n, other)))
            delta += -2 * pc[ko] + 1          # (v-1)^2 - v^2
            delta += 2 * pc[ki] + 1           # (v+1)^2 - v^2
        return delta

    def apply_swap(chain: list[int], out_n: int, in_n: int) -> None:
        for other in chain:
            if other in (out_n, in_n):
                continue
            pc[tuple(sorted((out_n, other)))] -= 1
            pc[tuple(sorted((in_n, other)))] += 1
        per_node[out_n] -= 1
        per_node[in_n] += 1
        chain[chain.index(out_n)] = in_n

    improved = True
    for _ in range(sweeps):
        if not improved:
            break
        improved = False
        order = list(range(num_chains))
        rng.shuffle(order)
        for ci in order:
            chain = assignment[ci]
            # move 1: single replacement within the balanced band
            best = (0, None, None)
            for out_n in chain:
                for in_n in nodes:
                    if in_n in chain:
                        continue
                    if per_node[out_n] - 1 < cap_lo or \
                            per_node[in_n] + 1 > cap_hi:
                        continue
                    d = swap_delta(chain, out_n, in_n)
                    if d < best[0]:
                        best = (d, out_n, in_n)
            d, out_n, in_n = best
            if out_n is not None:
                apply_swap(chain, out_n, in_n)
                improved = True
                continue
            # move 2: EXCHANGE members with another chain — per-node counts
            # are invariant, so this works even when the balanced band has
            # zero slack (num_chains*replicas divisible by num_nodes)
            cj = rng.randrange(num_chains)
            if cj == ci:
                continue
            other_chain = assignment[cj]
            best2 = (0, None, None)
            for a in chain:
                if a in other_chain:
                    continue
                for b in other_chain:
                    if b in chain:
                        continue
                    d1 = swap_delta(chain, a, b)
                    # apply tentatively so the second delta sees the first
                    apply_swap(chain, a, b)
                    d2 = swap_delta(other_chain, b, a)
                    apply_swap(chain, b, a)   # revert
                    if d1 + d2 < best2[0]:
                        best2 = (d1 + d2, a, b)
            d, a, b = best2
            if a is not None:
                apply_swap(chain, a, b)
                apply_swap(other_chain, b, a)
                improved = True
    return assignment


def chain_recovery_weights(routing: RoutingInfo,
                           failed_nodes: set[int]) -> dict[int, int]:
    """Per-chain STANDING recovery load implied by the placement when
    `failed_nodes` are down: each chain is weighted by how many of its
    member targets live on failed nodes (those chains are sourcing
    resync/degraded traffic already).  The EC repair planner seeds its
    survivor-pick counters with these exact weights instead of starting
    from zero, so stripe repairs steer AROUND chains the failure already
    loaded (the solver's pair-count objective, applied at repair time)."""
    weights: dict[int, int] = {}
    for cid, chain in routing.chains.items():
        w = sum(1 for t in chain.targets
                if t.node_id in failed_nodes)
        if w:
            weights[cid] = w
    return weights


def recovery_imbalance(assignment: list[list[int]], num_nodes: int) -> float:
    """max over failed nodes of (max peer recovery share / mean share);
    1.0 = perfectly balanced reconstruction traffic."""
    worst = 1.0
    for f in range(1, num_nodes + 1):
        load = recovery_load(assignment, num_nodes, f)
        if not load:
            continue
        mean = sum(load.values()) / max(1, num_nodes - 1)
        if mean > 0:
            worst = max(worst, max(load.values()) / mean)
    return worst
