"""Mgmtd: cluster manager service.

Reference analogs (SURVEY.md §2.4): MgmtdState (lease, MgmtdState.h:28),
MgmtdOperator ops (heartbeat, getRoutingInfo, setChainTable, updateChain...),
background MgmtdHeartbeatChecker (dead after T), MgmtdChainsUpdater applying
the LocalState x PublicState transition table (updateChain.h:38
generateNewChain; docs/design_notes.md:201-231), MgmtdLeaseExtender.

State lives in the transactional KV (same store as file metadata, like the
reference persists its lease/chains in FoundationDB); heartbeat liveness is
in-memory (a restarted mgmtd re-learns it within one heartbeat period).
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field

from t3fs.kv.engine import KVEngine, with_transaction
from t3fs.kv.prefixes import KeyPrefix
from t3fs.mgmtd.types import (
    ChainInfo, ChainTable, ChainTargetInfo, LocalTargetState, NodeInfo,
    PublicTargetState, RoutingInfo,
)
from t3fs.net.server import rpc_method, service
from t3fs.utils import serde
from t3fs.utils.config import ConfigBase, citem
from t3fs.utils.serde import serde_struct
from t3fs.utils.status import StatusCode, make_error

log = logging.getLogger("t3fs.mgmtd")


@serde_struct
@dataclass
class HeartbeatReq:
    node: NodeInfo = field(default_factory=NodeInfo)
    target_states: dict[int, LocalTargetState] = field(default_factory=dict)
    routing_version: int = 0


@serde_struct
@dataclass
class HeartbeatRsp:
    routing_version: int = 0
    primary: bool = True


@serde_struct
@dataclass
class GetRoutingInfoReq:
    known_version: int = 0


@serde_struct
@dataclass
class GetRoutingInfoRsp:
    info: RoutingInfo | None = None   # None when caller is up to date


@serde_struct
@dataclass
class SetChainsReq:
    chains: list[ChainInfo] = field(default_factory=list)
    tables: list[ChainTable] = field(default_factory=list)


@serde_struct
@dataclass
class OkRsp:
    ok: bool = True


@serde_struct
@dataclass
class LeaseInfo:
    holder_node: int = 0
    holder_address: str = ""
    expires_at: float = 0.0


@serde_struct
@dataclass
class NodeStatus:
    node: NodeInfo = field(default_factory=NodeInfo)
    last_heartbeat_age_s: float = -1.0
    alive: bool = False


@serde_struct
@dataclass
class ListNodesRsp:
    nodes: list[NodeStatus] = field(default_factory=list)


@serde_struct
@dataclass
class SetConfigTemplateReq:
    node_type: str = ""
    toml: str = ""


@serde_struct
@dataclass
class GetConfigTemplateReq:
    node_type: str = ""


@serde_struct
@dataclass
class GetConfigTemplateRsp:
    toml: str = ""
    found: bool = False


@dataclass
class MgmtdConfig(ConfigBase):
    """Hot-updatable service knobs (ConfigBase.h CONFIG_HOT_UPDATED_ITEM
    analog) — the background loops read these live each iteration."""
    heartbeat_timeout_s: float = citem(2.0, validator=lambda v: v > 0)
    chains_update_period_s: float = citem(0.25, validator=lambda v: v > 0)
    lease_ttl_s: float = citem(10.0, validator=lambda v: v > 0)
    lease_extend_period_s: float = citem(3.0, validator=lambda v: v > 0)


class MgmtdState:
    """Persistent cluster state over the KV + in-memory liveness."""

    def __init__(self, kv: KVEngine, node_id: int, address: str,
                 cfg: MgmtdConfig):
        self.kv = kv
        self.node_id = node_id
        self.address = address
        self.cfg = cfg
        self.last_heartbeat: dict[int, float] = {}
        self.local_states: dict[int, LocalTargetState] = {}   # target -> state
        # targets whose node silently restarted: demote from SERVING so they
        # resync (cleared by the chains updater AFTER a successful save)
        self.restarted_targets: set[int] = set()
        # node records whose generation changed: persisted by the chains
        # updater IN THE SAME transaction as the demotions, so an mgmtd
        # failover can't see the new generation without the demotions
        self.pending_node_saves: dict[int, NodeInfo] = {}
        self._routing_cache: RoutingInfo | None = None
        # startup grace: a restarted mgmtd has an empty liveness map — treat
        # every node as alive until one full heartbeat window has passed, or
        # the first updater tick would demote the whole healthy cluster
        self.started_at: float = time.time()

    # --- lease (primary election) ---

    async def try_acquire_lease(self) -> bool:
        now = time.time()

        async def txn_fn(txn):
            raw = await txn.get(KeyPrefix.LEASE.key())
            lease = serde.loads(raw) if raw else LeaseInfo()
            if lease.holder_node not in (0, self.node_id) and lease.expires_at > now:
                return False
            txn.set(KeyPrefix.LEASE.key(), serde.dumps(LeaseInfo(
                self.node_id, self.address, now + self.cfg.lease_ttl_s)))
            return True

        return await with_transaction(self.kv, txn_fn)

    async def is_primary(self) -> bool:
        txn = self.kv.transaction()
        raw = await txn.get(KeyPrefix.LEASE.key(), snapshot=True)
        if not raw:
            return False
        lease = serde.loads(raw)
        return lease.holder_node == self.node_id and lease.expires_at > time.time()

    async def lease_info(self) -> LeaseInfo:
        txn = self.kv.transaction()
        raw = await txn.get(KeyPrefix.LEASE.key(), snapshot=True)
        return serde.loads(raw) if raw else LeaseInfo()

    # --- persistent records ---

    async def load_routing(self) -> RoutingInfo:
        txn = self.kv.transaction()
        info = RoutingInfo()
        raw = await txn.get(KeyPrefix.ROUTING_VER.key(), snapshot=True)
        info.version = int(raw) if raw else 1
        for k, v in await txn.get_range(KeyPrefix.NODE.value, KeyPrefix.NODE.value + b"\xff",
                                  snapshot=True):
            n: NodeInfo = serde.loads(v)
            info.nodes[n.node_id] = n
        for k, v in await txn.get_range(KeyPrefix.CHAIN.value, KeyPrefix.CHAIN.value + b"\xff",
                                  snapshot=True):
            c: ChainInfo = serde.loads(v)
            info.chains[c.chain_id] = c
        for k, v in await txn.get_range(KeyPrefix.CHAIN_TABLE.value,
                                  KeyPrefix.CHAIN_TABLE.value + b"\xff", snapshot=True):
            t: ChainTable = serde.loads(v)
            info.chain_tables[t.table_id] = t
        self._routing_cache = info
        return info

    def routing(self) -> RoutingInfo:
        return self._routing_cache or RoutingInfo()

    async def save_node(self, node: NodeInfo) -> None:
        async def txn_fn(txn):
            txn.set(KeyPrefix.NODE.key(str(node.node_id).encode()), serde.dumps(node))
        await with_transaction(self.kv, txn_fn)

    async def save_chains(self, chains: list[ChainInfo],
                          tables: list[ChainTable] = (),
                          nodes: list[NodeInfo] = ()) -> None:
        """Persist chains (+tables, +node records) in ONE transaction — the
        nodes ride along so e.g. a restart-demotion and the node's new
        generation become durable together."""
        async def txn_fn(txn):
            for c in chains:
                txn.set(KeyPrefix.CHAIN.key(str(c.chain_id).encode()), serde.dumps(c))
            for t in tables or ():
                txn.set(KeyPrefix.CHAIN_TABLE.key(str(t.table_id).encode()),
                        serde.dumps(t))
            for n in nodes or ():
                txn.set(KeyPrefix.NODE.key(str(n.node_id).encode()),
                        serde.dumps(n))
            raw = await txn.get(KeyPrefix.ROUTING_VER.key())
            txn.set(KeyPrefix.ROUTING_VER.key(), str(int(raw or 1) + 1).encode())
        await with_transaction(self.kv, txn_fn)
        await self.load_routing()

    def node_alive(self, node_id: int) -> bool:
        now = time.time()
        hb = self.last_heartbeat.get(node_id)
        if hb is None:
            return now - self.started_at < self.cfg.heartbeat_timeout_s
        return now - hb < self.cfg.heartbeat_timeout_s


def next_chain_state(chain: ChainInfo,
                     alive: dict[int, bool],
                     local: dict[int, LocalTargetState],
                     restarted: set[int] = frozenset()) -> ChainInfo | None:
    """One step of the chain state machine (generateNewChain analog,
    mgmtd/service/updateChain.h:38; table at docs/design_notes.md:201-231).
    Returns a NEW ChainInfo with bumped version if anything changed."""
    targets = [ChainTargetInfo(t.target_id, t.node_id, t.public_state)
               for t in chain.targets]
    changed = False
    serving_count = sum(1 for t in targets
                        if t.public_state == PublicTargetState.SERVING)
    # survivors a restarted member can be demoted onto: serving, alive, and
    # not themselves freshly restarted — demoting onto a dead/restarted
    # "survivor" would leave the chain with no authoritative copy
    healthy_serving = sum(
        1 for t in targets
        if t.public_state == PublicTargetState.SERVING
        and alive.get(t.node_id, False) and t.target_id not in restarted)
    # if EVERY live serving member restarted (e.g. rack power blip), one of
    # them must stay as the survivor the others resync from — exempting the
    # head keeps the chain available; the rest still get demoted so replica
    # divergence from the restarts is repaired
    survivor_exempt: int | None = None
    if healthy_serving == 0:
        for t in targets:
            if t.public_state == PublicTargetState.SERVING \
                    and alive.get(t.node_id, False) \
                    and t.target_id in restarted:
                survivor_exempt = t.target_id
                break
    # a LASTSRV target holds the only authoritative copy: while one exists,
    # a returning stale target must NOT be seated as serving (write loss)
    has_lastsrv = any(t.public_state == PublicTargetState.LASTSRV
                      for t in targets)
    for t in targets:
        a = alive.get(t.node_id, False)
        ls = local.get(t.target_id, LocalTargetState.INVALID)
        if t.public_state == PublicTargetState.SERVING and a \
                and t.target_id in restarted \
                and (healthy_serving >= 1 or t.target_id != survivor_exempt):
            # node restarted within the heartbeat window: its data may be
            # stale/lost while it still looks alive — demote to SYNCING so
            # resync re-validates it (sole survivor keeps serving: its copy,
            # whatever remains of it, is the best the chain has)
            t.public_state = PublicTargetState.SYNCING
            serving_count -= 1
            changed = True
        elif t.public_state == PublicTargetState.SERVING and not a:
            # last serving target holds the authoritative copy: LASTSRV
            t.public_state = (PublicTargetState.LASTSRV if serving_count == 1
                              else PublicTargetState.OFFLINE)
            serving_count -= 1
            changed = True
        elif t.public_state == PublicTargetState.SYNCING and not a:
            t.public_state = PublicTargetState.OFFLINE
            changed = True
        elif t.public_state == PublicTargetState.LASTSRV and a:
            t.public_state = PublicTargetState.SERVING
            serving_count += 1
            has_lastsrv = False
            changed = True
        elif t.public_state in (PublicTargetState.OFFLINE, PublicTargetState.WAITING) \
                and a and ls in (LocalTargetState.ONLINE, LocalTargetState.UPTODATE):
            if serving_count > 0:
                t.public_state = PublicTargetState.SYNCING   # rejoin at tail
                changed = True
            elif not has_lastsrv:
                # true cold start (nobody ever served or everyone wiped):
                # the returning target seeds the chain
                t.public_state = PublicTargetState.SERVING
                serving_count += 1
                changed = True
            # else: wait for the LASTSRV holder — it has the newest data
        elif t.public_state == PublicTargetState.SYNCING and a \
                and ls == LocalTargetState.UPTODATE:
            t.public_state = PublicTargetState.SERVING       # promoted to tail
            serving_count += 1
            changed = True
    if not changed:
        return None
    # canonical order: serving (original order), then syncing, then the rest —
    # offline targets move to the chain tail (design_notes.md:226)
    order = {PublicTargetState.SERVING: 0, PublicTargetState.SYNCING: 1,
             PublicTargetState.LASTSRV: 2, PublicTargetState.WAITING: 3,
             PublicTargetState.OFFLINE: 4}
    targets.sort(key=lambda t: order[t.public_state])
    return ChainInfo(chain.chain_id, chain.chain_ver + 1, targets)


@service("Mgmtd")
class MgmtdService:
    """RPC surface (fbs/mgmtd/MgmtdServiceDef.h:3-26 subset)."""

    def __init__(self, state: MgmtdState):
        self.state = state

    async def _require_primary(self):
        if not await self.state.is_primary():
            raise make_error(StatusCode.MGMTD_NOT_PRIMARY,
                             f"mgmtd {self.state.node_id} lost the lease")

    @rpc_method
    async def heartbeat(self, req: HeartbeatReq, payload, conn):
        await self._require_primary()
        st = self.state
        known = st.routing().nodes.get(req.node.node_id)
        st.last_heartbeat[req.node.node_id] = time.time()
        # generation is PERSISTED with the node record, so restart
        # detection survives an mgmtd restart/failover coinciding with
        # the storage node's restart
        prev_gen = known.generation if known is not None else None
        restarted = (req.node.generation and prev_gen
                     and prev_gen != req.node.generation)
        if restarted:
            # fast restart (within the heartbeat window): every target
            # this node serves must fall back to SYNCING and resync.
            # The new generation is NOT persisted here — the chains
            # updater saves it atomically with the demotions, so a
            # primary failover can't observe the generation without them.
            for chain in st.routing().chains.values():
                for t in chain.targets:
                    if t.node_id == req.node.node_id:
                        st.restarted_targets.add(t.target_id)
            st.pending_node_saves[req.node.node_id] = req.node
        for tid, ls in req.target_states.items():
            st.local_states[int(tid)] = LocalTargetState(ls)
        if not restarted and (known is None
                              or known.address != req.node.address
                              or known.generation != req.node.generation):
            await st.save_node(req.node)
            await st.load_routing()
        return HeartbeatRsp(routing_version=st.routing().version), b""

    @rpc_method
    async def get_routing_info(self, req: GetRoutingInfoReq, payload, conn):
        info = self.state.routing()
        if req.known_version >= info.version:
            return GetRoutingInfoRsp(info=None), b""
        return GetRoutingInfoRsp(info=info), b""

    @rpc_method
    async def set_chains(self, req: SetChainsReq, payload, conn):
        """Admin op: install chains/chain tables (UploadChainTable analog)."""
        await self._require_primary()
        await self.state.save_chains(req.chains, req.tables)
        return OkRsp(), b""

    @rpc_method
    async def list_nodes(self, req, payload, conn):
        """Admin op (ListNodes analog): registered nodes + liveness."""
        st = self.state
        now = time.time()
        rows = []
        for node in st.routing().nodes.values():
            hb = st.last_heartbeat.get(node.node_id, 0.0)
            rows.append(NodeStatus(
                node=node, last_heartbeat_age_s=(now - hb) if hb else -1.0,
                alive=st.node_alive(node.node_id)))
        return ListNodesRsp(rows), b""

    @rpc_method
    async def get_lease(self, req, payload, conn):
        """Who is primary (MgmtdLeaseInfo analog)."""
        lease = await self.state.lease_info()
        return lease, b""

    @rpc_method
    async def set_config_template(self, req: SetConfigTemplateReq, payload, conn):
        """Store a per-node-type config template in the KV — the config-
        distribution half of the two-phase bootstrap (reference:
        TwoPhaseApplication.h:42-46, core/app/MgmtdClientFetcher.h)."""
        await self._require_primary()

        async def op(txn):
            txn.set(KeyPrefix.CONFIG.key(req.node_type.encode()),
                    req.toml.encode())
        await with_transaction(self.state.kv, op)
        return OkRsp(), b""

    @rpc_method
    async def get_config_template(self, req: GetConfigTemplateReq, payload, conn):
        async def op(txn):
            return await txn.get(KeyPrefix.CONFIG.key(req.node_type.encode()))
        raw = await with_transaction(self.state.kv, op)
        return GetConfigTemplateRsp(
            toml=raw.decode() if raw is not None else "",
            found=raw is not None), b""


class MgmtdServer:
    """State + service + background loops (chains updater, lease extender)."""

    def __init__(self, kv: KVEngine, node_id: int = 1, address: str = "",
                 cfg: MgmtdConfig | None = None, admin_token: str = ""):
        self.cfg = cfg or MgmtdConfig()
        self.state = MgmtdState(kv, node_id, address, self.cfg)
        self.service = MgmtdService(self.state)
        from t3fs.core.service import AppInfo, CoreService
        self.core = CoreService(AppInfo(node_id, "mgmtd", address),
                                config=self.cfg, kv=kv, admin_token=admin_token)
        self._tasks: list[asyncio.Task] = []
        self._stopped = asyncio.Event()

    @property
    def services(self):
        """Everything to register on the net server (reference registers
        MgmtdService + CoreService, MgmtdServer.cc:33-34)."""
        return [self.service, self.core]

    async def start(self) -> None:
        acquired = await self.state.try_acquire_lease()
        if acquired:
            log.info("mgmtd %d acquired primary lease", self.state.node_id)
        await self.state.load_routing()
        self._tasks = [
            asyncio.create_task(self._chains_updater(), name="mgmtd-chains"),
            asyncio.create_task(self._lease_extender(), name="mgmtd-lease"),
        ]

    async def stop(self) -> None:
        self._stopped.set()
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass

    async def _lease_extender(self) -> None:
        while not self._stopped.is_set():
            await asyncio.sleep(self.cfg.lease_extend_period_s)
            try:
                await self.state.try_acquire_lease()
            except Exception:
                log.exception("lease extension failed")

    async def _chains_updater(self) -> None:
        """Primary-only periodic scan applying the chain state machine
        (MgmtdChainsUpdater.cc:72 analog)."""
        while not self._stopped.is_set():
            await asyncio.sleep(self.cfg.chains_update_period_s)
            try:
                if not await self.state.is_primary():
                    continue
                await self.update_chains_once()
            except Exception:
                log.exception("chains updater failed")

    async def update_chains_once(self) -> int:
        """One updater tick; returns number of chains changed (test hook)."""
        st = self.state
        routing = st.routing()
        updated = []
        handled: set[int] = set()
        for chain in routing.chains.values():
            alive = {t.node_id: st.node_alive(t.node_id) for t in chain.targets}
            nxt = next_chain_state(chain, alive, st.local_states,
                                   restarted=st.restarted_targets)
            handled |= {t.target_id for t in chain.targets} \
                & st.restarted_targets
            if nxt is not None:
                updated.append(nxt)
                log.info("chain %d v%d -> v%d: %s", nxt.chain_id,
                         chain.chain_ver, nxt.chain_ver,
                         [(t.target_id, t.public_state.name) for t in nxt.targets])
        pending_nodes = list(st.pending_node_saves.values())
        if updated or pending_nodes:
            # demotions and the new node generations land in ONE txn
            await st.save_chains(updated, nodes=pending_nodes)
        # only forget restart flags once the demotions are durably saved —
        # dropping them before a failed save would leave a stale node
        # serving forever
        st.restarted_targets -= handled
        for n in pending_nodes:
            st.pending_node_saves.pop(n.node_id, None)
        return len(updated)
