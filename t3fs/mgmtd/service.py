"""Mgmtd: cluster manager service.

Reference analogs (SURVEY.md §2.4): MgmtdState (lease, MgmtdState.h:28),
MgmtdOperator ops (heartbeat, getRoutingInfo, setChainTable, updateChain...),
background MgmtdHeartbeatChecker (dead after T), MgmtdChainsUpdater applying
the LocalState x PublicState transition table (updateChain.h:38
generateNewChain; docs/design_notes.md:201-231), MgmtdLeaseExtender.

State lives in the transactional KV (same store as file metadata, like the
reference persists its lease/chains in FoundationDB); heartbeat liveness is
in-memory (a restarted mgmtd re-learns it within one heartbeat period).
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field

from t3fs.kv.engine import KVEngine, with_transaction
from t3fs.kv.prefixes import KeyPrefix
from t3fs.mgmtd.types import (
    ChainInfo, ChainTable, ChainTargetInfo, ClientSession, LocalTargetState,
    NodeInfo, PublicTargetState, RoutingInfo,
)
from t3fs.mgmtd.types import NodeStatus as NodeStatusEnum
from t3fs.monitor.health import ClusterHealth
from t3fs.net.server import rpc_method, service
from t3fs.net.wire import OkRsp
from t3fs.utils import serde
from t3fs.utils.aio import reap_task
from t3fs.utils.config import ConfigBase, citem
from t3fs.utils.serde import serde_struct
from t3fs.utils.status import StatusCode, make_error

log = logging.getLogger("t3fs.mgmtd")


@serde_struct
@dataclass
class HeartbeatReq:
    node: NodeInfo = field(default_factory=NodeInfo)
    target_states: dict[int, LocalTargetState] = field(default_factory=dict)
    routing_version: int = 0
    # targets whose engine booted on a VIRGIN directory and have not yet
    # completed a resync: the chain state machine must never reseat such
    # a target as an authority (fresh-LASTSRV demotion; append-only
    # field — old nodes simply never report any)
    fresh_targets: list[int] = field(default_factory=list)


@serde_struct
@dataclass
class HeartbeatRsp:
    routing_version: int = 0
    primary: bool = True
    # mgmtd's failure-detection window: the node self-fences (stops
    # serving writes) when it hasn't completed a heartbeat for lease_s/2,
    # so a partitioned stale head stops acking BEFORE mgmtd promotes a
    # successor (reference: suicide at lease/2, src/common/utils/
    # suicide.cc:7, docs/design_notes.md:177)
    lease_s: float = 0.0


@serde_struct
@dataclass
class GetRoutingInfoReq:
    known_version: int = 0
    # appended (serde add-only, like PR 11's trace fields): scorecard
    # version the caller already holds; 0 asks for whatever is cached
    known_health_version: int = 0
    # appended (ISSUE 15): caller can apply RoutingDelta — opt-in,
    # because a pre-15 client interprets info=None as "up to date" and
    # would silently drop an unsolicited delta
    want_delta: bool = False


@serde_struct
@dataclass
class RoutingDelta:
    """Incremental routing update (ISSUE 15): only the chains that
    changed between base_version and version, plus the (small) full node
    and chain-table maps.  A caller whose cached version != base_version
    must discard the delta and do a full refresh."""
    version: int = 0
    base_version: int = 0
    chains: list[ChainInfo] = field(default_factory=list)
    removed_chains: list[int] = field(default_factory=list)
    nodes: dict[int, NodeInfo] = field(default_factory=dict)
    chain_tables: dict[int, ChainTable] = field(default_factory=dict)
    bootstrapping: bool = False


@serde_struct
@dataclass
class GetRoutingInfoRsp:
    info: RoutingInfo | None = None   # None when caller is up to date
    # appended (add-only): cluster health scorecard piggyback — present
    # when the primary has pulled one from the monitor AND the caller's
    # known_health_version is behind; old clients drop the extra fields,
    # old servers leave them at defaults (None/0)
    health: ClusterHealth | None = None
    health_version: int = 0
    # appended (ISSUE 15): incremental update when the caller sent
    # want_delta and the change log covers its version; info stays None
    delta: RoutingDelta | None = None


@serde_struct
@dataclass
class ClusterHealthReq:
    pass


@serde_struct
@dataclass
class ClusterHealthRsp:
    health: ClusterHealth | None = None
    health_version: int = 0


@serde_struct
@dataclass
class SetChainsReq:
    chains: list[ChainInfo] = field(default_factory=list)
    tables: list[ChainTable] = field(default_factory=list)


@serde_struct
@dataclass
class LeaseInfo:
    holder_node: int = 0
    holder_address: str = ""
    expires_at: float = 0.0


@serde_struct
@dataclass
class NodeStatus:
    node: NodeInfo = field(default_factory=NodeInfo)
    last_heartbeat_age_s: float = -1.0
    alive: bool = False


@serde_struct
@dataclass
class ListNodesRsp:
    nodes: list[NodeStatus] = field(default_factory=list)


@serde_struct
@dataclass
class SetConfigTemplateReq:
    node_type: str = ""
    toml: str = ""


@serde_struct
@dataclass
class GetConfigTemplateReq:
    node_type: str = ""


@serde_struct
@dataclass
class GetConfigTemplateRsp:
    toml: str = ""
    found: bool = False


@dataclass
class MgmtdConfig(ConfigBase):
    """Hot-updatable service knobs (ConfigBase.h CONFIG_HOT_UPDATED_ITEM
    analog) — the background loops read these live each iteration."""
    heartbeat_timeout_s: float = citem(2.0, validator=lambda v: v > 0)
    chains_update_period_s: float = citem(0.25, validator=lambda v: v > 0)
    lease_ttl_s: float = citem(10.0, validator=lambda v: v > 0)
    lease_extend_period_s: float = citem(3.0, validator=lambda v: v > 0)
    client_session_ttl_s: float = citem(60.0, validator=lambda v: v > 0)
    sessions_check_period_s: float = citem(5.0, validator=lambda v: v > 0)
    # cluster health plane (ISSUE 14): the primary pulls the scorecard
    # from the monitor and piggybacks it on GetRoutingInfoRsp.  Empty
    # monitor_address disables the puller (pre-health deployments)
    monitor_address: str = citem("")
    health_pull_period_s: float = citem(1.0, validator=lambda v: v > 0)
    health_window_s: float = citem(30.0, validator=lambda v: v > 0)


class MgmtdState:
    """Persistent cluster state over the KV + in-memory liveness."""

    def __init__(self, kv: KVEngine, node_id: int, address: str,
                 cfg: MgmtdConfig):
        self.kv = kv
        self.node_id = node_id
        self.address = address
        self.cfg = cfg
        self.last_heartbeat: dict[int, float] = {}
        self.local_states: dict[int, LocalTargetState] = {}   # target -> state
        # targets currently reporting a virgin disk (HeartbeatReq.
        # fresh_targets); in-memory like local_states — re-learned from
        # the next heartbeats after an mgmtd restart
        self.fresh_targets: set[int] = set()
        self._persisted_states: dict[int, LocalTargetState] = {}
        # targets whose node silently restarted: demote from SERVING so they
        # resync (cleared by the chains updater AFTER a successful save)
        self.restarted_targets: set[int] = set()
        # node records whose generation changed: persisted by the chains
        # updater IN THE SAME transaction as the demotions, so an mgmtd
        # failover can't see the new generation without the demotions
        self.pending_node_saves: dict[int, NodeInfo] = {}
        self._routing_cache: RoutingInfo | None = None
        # which node last reported each target (live info from heartbeats;
        # feeds listOrphanTargets — not persisted, best-effort by design)
        self.target_reporter: dict[int, int] = {}
        # latest scrub/repair health per reporting source (pushed by
        # report_repair_status; in-memory like last_heartbeat)
        self.repair_statuses: dict[str, "RepairStatus"] = {}
        # cluster health scorecard pulled from the monitor (in-memory,
        # like liveness: re-pulled within one period after a failover).
        # health_version bumps on every refreshed pull so clients can
        # version-gate the GetRoutingInfoRsp piggyback
        self.health: ClusterHealth | None = None
        self.health_version: int = 0
        # startup grace: a restarted mgmtd has an empty liveness map — treat
        # every node as alive until one full heartbeat window has passed, or
        # the first updater tick would demote the whole healthy cluster
        self.started_at: float = time.time()
        # ISSUE 15: routing change log — version -> chain ids changed at
        # that version (empty tuple = node/table-only bump).  In-memory:
        # a restarted/failed-over mgmtd starts with an empty log and
        # clients simply fall back to one full refresh.  Any version
        # missing from the window forces the full path too, so the log
        # can never serve a delta it cannot prove complete.
        self.change_log: dict[int, tuple[int, ...]] = {}

    CHANGE_LOG_CAP = 256

    def _log_change(self, version: int, chain_ids) -> None:
        prev = self.change_log.get(version, ())
        self.change_log[version] = tuple(set(prev) | set(chain_ids))
        while len(self.change_log) > self.CHANGE_LOG_CAP:
            self.change_log.pop(min(self.change_log))

    def build_delta(self, known_version: int) -> "RoutingDelta | None":
        """Delta covering (known_version, current]; None when the change
        log cannot prove completeness (gap, restart, or caller too far
        behind) — the caller then gets the full RoutingInfo."""
        info = self.routing()
        if known_version <= 0 or known_version >= info.version:
            return None
        changed: set[int] = set()
        for v in range(known_version + 1, info.version + 1):
            entry = self.change_log.get(v)
            if entry is None:
                return None
            changed.update(entry)
        if len(changed) * 2 >= max(1, len(info.chains)):
            return None            # most chains moved: full is cheaper
        return RoutingDelta(
            version=info.version, base_version=known_version,
            chains=[info.chains[c] for c in sorted(changed)
                    if c in info.chains],
            removed_chains=sorted(c for c in changed
                                  if c not in info.chains),
            nodes=dict(info.nodes),
            chain_tables=dict(info.chain_tables),
            bootstrapping=info.bootstrapping)

    # --- lease (primary election) ---

    async def try_acquire_lease(self) -> bool:
        now = time.time()

        async def txn_fn(txn):
            raw = await txn.get(KeyPrefix.LEASE.key())
            lease = serde.loads(raw) if raw else LeaseInfo()
            if lease.holder_node not in (0, self.node_id) and lease.expires_at > now:
                return False
            txn.set(KeyPrefix.LEASE.key(), serde.dumps(LeaseInfo(
                self.node_id, self.address, now + self.cfg.lease_ttl_s)))
            return True

        return await with_transaction(self.kv, txn_fn)

    async def is_primary(self) -> bool:
        txn = self.kv.transaction()
        raw = await txn.get(KeyPrefix.LEASE.key(), snapshot=True)
        if not raw:
            return False
        lease = serde.loads(raw)
        return lease.holder_node == self.node_id and lease.expires_at > time.time()

    async def lease_info(self) -> LeaseInfo:
        txn = self.kv.transaction()
        raw = await txn.get(KeyPrefix.LEASE.key(), snapshot=True)
        return serde.loads(raw) if raw else LeaseInfo()

    # --- persistent records ---

    async def load_routing(self) -> RoutingInfo:
        txn = self.kv.transaction()
        info = RoutingInfo()
        raw = await txn.get(KeyPrefix.ROUTING_VER.key(), snapshot=True)
        info.version = int(raw) if raw else 1
        for k, v in await txn.get_range(KeyPrefix.NODE.value, KeyPrefix.NODE.value + b"\xff",
                                  snapshot=True):
            n: NodeInfo = serde.loads(v)
            info.nodes[n.node_id] = n
        for k, v in await txn.get_range(KeyPrefix.CHAIN.value, KeyPrefix.CHAIN.value + b"\xff",
                                  snapshot=True):
            c: ChainInfo = serde.loads(v)
            info.chains[c.chain_id] = c
        for k, v in await txn.get_range(KeyPrefix.CHAIN_TABLE.value,
                                  KeyPrefix.CHAIN_TABLE.value + b"\xff", snapshot=True):
            t: ChainTable = serde.loads(v)
            info.chain_tables[t.table_id] = t
        if not self.local_states:
            # fresh/failed-over mgmtd: seed target info from the persisted
            # blob (heartbeats overwrite it live)
            raw = await txn.get(KeyPrefix.TARGET_INFO.key(), snapshot=True)
            if raw:
                blob: "TargetInfoBlob" = serde.loads(raw)
                self.local_states = {int(k2): LocalTargetState(v2)
                                     for k2, v2 in blob.states.items()}
        self._routing_cache = info
        return info

    async def persist_target_info(self) -> None:
        """Persist the current per-target local states (one blob)."""
        states = dict(self.local_states)

        async def txn_fn(txn):
            txn.set(KeyPrefix.TARGET_INFO.key(),
                    serde.dumps(TargetInfoBlob(states=states)))
        await with_transaction(self.kv, txn_fn)
        self._persisted_states = states

    def routing(self) -> RoutingInfo:
        return self._routing_cache or RoutingInfo()

    @staticmethod
    async def _merge_node_write(txn, node: NodeInfo,
                                admin: bool) -> NodeInfo:
        """In-txn merge for node-record writes.  status (when DISABLED) and
        tags are mgmtd-admin-owned: liveness/heartbeat writers must never
        stomp them, and reading the current record inside the transaction
        makes a racing admin op an SSI conflict instead of a lost update."""
        key = KeyPrefix.NODE.key(str(node.node_id).encode())
        if not admin:
            raw = await txn.get(key)
            if raw is not None:
                cur: NodeInfo = serde.loads(raw)
                merged = NodeInfo(**{**node.__dict__})
                merged.tags = list(cur.tags)
                if cur.status == NodeStatusEnum.DISABLED:
                    merged.status = cur.status
                node = merged
        txn.set(key, serde.dumps(node))
        return node

    async def save_node(self, node: NodeInfo) -> None:
        async def txn_fn(txn):
            await self._merge_node_write(txn, node, admin=False)
        await with_transaction(self.kv, txn_fn)

    async def save_chains(self, chains: list[ChainInfo],
                          tables: list[ChainTable] = (),
                          nodes: list[NodeInfo] = (),
                          guard_versions: bool = True) -> list[int]:
        """Persist chains (+tables, +node records) in ONE transaction — the
        nodes ride along so e.g. a restart-demotion and the node's new
        generation become durable together.

        Each chain write is CAS-guarded inside the transaction: a chain is
        only stored if the persisted version is exactly new_ver - 1.  The
        chains updater and the admin chain-surgery ops both read-modify-write
        from the in-memory routing cache, so without the guard whichever
        transaction commits second would silently revert the first (both
        also touch ROUTING_VER, so SSI aborts one — but with_transaction
        re-runs the closure with the same stale pre-computed value; the
        in-txn version check is what makes the retry correct).  Returns the
        chain ids actually written; skipped chains signal a lost race —
        callers recompute from fresh routing.  guard_versions=False is for
        installing chains wholesale (admin set_chains)."""
        written: list[int] = []

        async def txn_fn(txn):
            written.clear()
            any_write = False
            skipped = False
            for c in chains:
                key = KeyPrefix.CHAIN.key(str(c.chain_id).encode())
                if guard_versions:
                    raw = await txn.get(key)
                    cur_ver = serde.loads(raw).chain_ver if raw else 0
                    if cur_ver != c.chain_ver - 1:
                        skipped = True
                        continue  # someone else advanced this chain: skip
                txn.set(key, serde.dumps(c))
                written.append(c.chain_id)
                any_write = True
            for t in tables or ():
                # table_ver advances monotonically on every re-install
                # (ISSUE 15): read the persisted predecessor inside the
                # txn so with_transaction retries recompute it
                key = KeyPrefix.CHAIN_TABLE.key(str(t.table_id).encode())
                raw = await txn.get(key)
                prev = serde.loads(raw) if raw else None
                prev_ver = getattr(prev, "table_ver", 0) if prev else 0
                stamped = ChainTable(
                    table_id=t.table_id, chain_ids=list(t.chain_ids),
                    table_ver=max(prev_ver + 1, t.table_ver),
                    table_type=t.table_type,
                    # desired replication is sticky: a re-install that
                    # leaves it unset (0) must not erase the persisted
                    # value the solver depends on
                    replicas=getattr(t, "replicas", 0)
                    or (getattr(prev, "replicas", 0) if prev else 0))
                txn.set(key, serde.dumps(stamped))
                any_write = True
            if not skipped:
                # node-generation records ride ONLY when every guarded chain
                # landed: persisting a restarted node's generation without
                # its demotions would lose restart detection on a failover
                for n in nodes or ():
                    await self._merge_node_write(txn, n, admin=False)
                    any_write = True
            if any_write:
                raw = await txn.get(KeyPrefix.ROUTING_VER.key())
                txn.set(KeyPrefix.ROUTING_VER.key(),
                        str(int(raw or 1) + 1).encode())
            return any_write
        bumped = await with_transaction(self.kv, txn_fn)
        await self.load_routing()
        if bumped:
            # attribute the changed chains to (at least) the version the
            # reload observed — attributing too high is safe (a caller at
            # that version already holds the change), and a racing writer
            # colliding on the same version merges via _log_change
            self._log_change(self._routing_cache.version, written)
        return written

    def node_alive(self, node_id: int) -> bool:
        now = time.time()
        hb = self.last_heartbeat.get(node_id)
        if hb is None:
            return now - self.started_at < self.cfg.heartbeat_timeout_s
        return now - hb < self.cfg.heartbeat_timeout_s

    def node_serviceable(self, node_id: int) -> bool:
        """Alive AND not administratively disabled: the chains updater
        drains a DISABLED node's targets exactly like a dead node's
        (reference disableNode semantics, MgmtdServiceDef.h:10)."""
        if not self.node_alive(node_id):
            return False
        n = self.routing().nodes.get(node_id)
        return n is None or n.status != NodeStatusEnum.DISABLED


def next_chain_state(chain: ChainInfo,
                     alive: dict[int, bool],
                     local: dict[int, LocalTargetState],
                     restarted: set[int] = frozenset(),
                     fresh: set[int] = frozenset()) -> ChainInfo | None:
    """One step of the chain state machine (generateNewChain analog,
    mgmtd/service/updateChain.h:38; table at docs/design_notes.md:201-231).
    Returns a NEW ChainInfo with bumped version if anything changed."""
    targets = [ChainTargetInfo(t.target_id, t.node_id, t.public_state)
               for t in chain.targets]
    changed = False
    serving_count = sum(1 for t in targets
                        if t.public_state == PublicTargetState.SERVING)
    # survivors a restarted member can be demoted onto: serving, alive,
    # disk intact, and not themselves freshly restarted — demoting onto a
    # dead/dying/restarted "survivor" would leave the chain with no
    # authoritative copy.  Counting a local-OFFLINE (disk-dead) member as
    # healthy let one tick demote EVERY member at once, after which a
    # replaced-EMPTY disk cold-start-seeded the chain and resync erased
    # the real data from everyone (wide craq_sim sweep, seed 400084)
    healthy_serving = sum(
        1 for t in targets
        if t.public_state == PublicTargetState.SERVING
        and alive.get(t.node_id, False) and t.target_id not in restarted
        and local.get(t.target_id, LocalTargetState.INVALID)
        != LocalTargetState.OFFLINE)
    # if EVERY live serving member restarted (e.g. rack power blip), one of
    # them must stay as the survivor the others resync from — exempting the
    # head keeps the chain available; the rest still get demoted so replica
    # divergence from the restarts is repaired
    survivor_exempt: int | None = None
    if healthy_serving == 0:
        for t in targets:
            if t.public_state == PublicTargetState.SERVING \
                    and alive.get(t.node_id, False) \
                    and t.target_id in restarted \
                    and local.get(t.target_id, LocalTargetState.INVALID) \
                    != LocalTargetState.OFFLINE:
                # a disk-dead member cannot be the survivor the others
                # resync from — exempting it wastes the exemption and can
                # end the tick with zero serving and no LASTSRV
                survivor_exempt = t.target_id
                break
    # a LASTSRV target holds the only authoritative copy: while one exists,
    # a returning stale target must NOT be seated as serving (write loss)
    has_lastsrv = any(t.public_state == PublicTargetState.LASTSRV
                      for t in targets)
    # an alive, disk-ok SYNCING member (pass-start view): gates fresh
    # rejoiners out of the cold-start seed so real data wins the chain
    has_live_syncing = any(
        t.public_state == PublicTargetState.SYNCING
        and alive.get(t.node_id, False)
        and local.get(t.target_id, LocalTargetState.INVALID)
        != LocalTargetState.OFFLINE
        for t in targets)
    new_lastsrv = False                 # minted during THIS pass
    for t in targets:
        a = alive.get(t.node_id, False)
        ls = local.get(t.target_id, LocalTargetState.INVALID)
        if t.public_state == PublicTargetState.SERVING and a \
                and t.target_id in restarted \
                and (healthy_serving >= 1 or t.target_id != survivor_exempt):
            # node restarted within the heartbeat window: its data may be
            # stale/lost while it still looks alive — demote to SYNCING so
            # resync re-validates it (sole survivor keeps serving: its copy,
            # whatever remains of it, is the best the chain has)
            t.public_state = PublicTargetState.SYNCING
            serving_count -= 1
            changed = True
        elif t.public_state == PublicTargetState.SERVING \
                and (not a or ls == LocalTargetState.OFFLINE):
            # node dead OR the node itself reports the target's disk failed
            # (CheckWorker/write-error -> heartbeat local OFFLINE, reference
            # StorageOperator.cc:604-606); last serving target holds the
            # authoritative copy: LASTSRV
            if serving_count == 1:
                t.public_state = PublicTargetState.LASTSRV
                # visible to LATER targets in this same pass: without this,
                # an empty just-replaced disk processed after the demotion
                # cold-start-seeded itself as the authority and resync then
                # erased every real copy (wide craq_sim sweep, seed 400908)
                has_lastsrv = True
                new_lastsrv = True
            else:
                t.public_state = PublicTargetState.OFFLINE
            serving_count -= 1
            changed = True
        elif t.public_state == PublicTargetState.SYNCING \
                and (not a or ls == LocalTargetState.OFFLINE):
            t.public_state = PublicTargetState.OFFLINE
            changed = True
        elif t.public_state == PublicTargetState.LASTSRV and a \
                and t.target_id in fresh:
            # the lastsrv came back on a VIRGIN disk (heartbeat fresh
            # flag: wiped/replaced since it held the authority) — it has
            # nothing to serve, and reseating it would make resync ERASE
            # every surviving copy (mega-sweep seed 2802880: a wiped
            # 2-replica lastsrv reseated and removed the syncing
            # member's committed write).  Its lineage is gone: demote;
            # the orphan-promotion below seats the best remaining copy.
            t.public_state = PublicTargetState.OFFLINE
            has_lastsrv = False
            changed = True
        elif t.public_state == PublicTargetState.LASTSRV and a \
                and ls != LocalTargetState.OFFLINE:
            if serving_count > 0 or new_lastsrv:
                # SUPERSEDED lastsrv: while it was down the chain found
                # another authority (an UPTODATE syncing member promoted,
                # or a newer LASTSRV was minted this very pass), so its
                # copy is no longer the lineage — and after a restart it
                # may be wiped entirely.  Reseating it as SERVING forked
                # the authority and the next resync propagated its EMPTY
                # disk to the whole chain (hard-matrix craq sweep, seed
                # 990583: crash+wipe+disk-fail combined — acked-write
                # loss).  Rejoin as SYNCING and resync from the living
                # authority instead.
                t.public_state = PublicTargetState.SYNCING
                # THIS target stops being lastsrv, but one minted earlier
                # in the same pass still holds the authority: clearing
                # the flag here let a later empty rejoiner cold-start
                # seed as SERVING past it (code-review r4)
                has_lastsrv = new_lastsrv
            else:
                t.public_state = PublicTargetState.SERVING
                serving_count += 1
                has_lastsrv = False
            changed = True
        elif t.public_state == PublicTargetState.LASTSRV \
                and (not a or ls == LocalTargetState.OFFLINE) \
                and (serving_count > 0 or new_lastsrv):
            # the lastsrv died/lost its disk AFTER other members resynced
            # back to SERVING: its copy is no longer unique, so it must
            # demote like any failed member — otherwise it stays LASTSRV
            # forever, can never be disk-replaced (the operator gate only
            # swaps OFFLINE/WAITING targets), and wedges the chain at
            # less-than-full strength (wide craq_sim sweep, seed 400014).
            # Also fires when a NEWER lastsrv was minted this pass — two
            # coexisting LASTSRVs would both reseat as SERVING on return
            # with no resync between them (review-found divergence)
            t.public_state = PublicTargetState.OFFLINE
            has_lastsrv = False
            changed = True
        elif t.public_state in (PublicTargetState.OFFLINE, PublicTargetState.WAITING) \
                and a and ls in (LocalTargetState.ONLINE, LocalTargetState.UPTODATE):
            if serving_count > 0:
                t.public_state = PublicTargetState.SYNCING   # rejoin at tail
                changed = True
            elif not has_lastsrv and not (
                    t.target_id in fresh and has_live_syncing):
                # true cold start (nobody ever served or everyone wiped):
                # the returning target seeds the chain.  A FRESH (virgin
                # disk) rejoiner must not seed past an alive SYNCING
                # member holding real data — the orphan promotion below
                # seats that copy instead (code-review r4: the seed
                # branch was a second door to the 2802880 loss)
                t.public_state = PublicTargetState.SERVING
                serving_count += 1
                changed = True
            # else: wait for the LASTSRV holder — it has the newest data
        elif t.public_state == PublicTargetState.SYNCING and a \
                and ls == LocalTargetState.UPTODATE:
            t.public_state = PublicTargetState.SERVING       # promoted to tail
            serving_count += 1
            changed = True
    # orphan promotion: zero serving members and no authoritative
    # lastsrv left (e.g. the lastsrv returned on a virgin disk), but an
    # alive disk-ok SYNCING member exists — its copy, pre-join gap and
    # all, is the BEST the chain still has; seat it so the survivors
    # resync from real data instead of an empty disk.  Prefer a
    # non-fresh member (one that completed a resync or joined with
    # data) over a fresh one.
    if serving_count == 0 and not has_lastsrv:
        candidates = [t for t in targets
                      if t.public_state == PublicTargetState.SYNCING
                      and alive.get(t.node_id, False)
                      and local.get(t.target_id, LocalTargetState.INVALID)
                      != LocalTargetState.OFFLINE]
        candidates.sort(key=lambda t: t.target_id in fresh)
        if candidates:
            candidates[0].public_state = PublicTargetState.SERVING
            serving_count += 1
            changed = True
    if not changed:
        return None
    # canonical order: serving (original order), then syncing, then the rest —
    # offline targets move to the chain tail (design_notes.md:226)
    order = {PublicTargetState.SERVING: 0, PublicTargetState.SYNCING: 1,
             PublicTargetState.LASTSRV: 2, PublicTargetState.WAITING: 3,
             PublicTargetState.OFFLINE: 4}
    targets.sort(key=lambda t: order[t.public_state])
    return ChainInfo(chain.chain_id, chain.chain_ver + 1, targets,
                     list(chain.preferred_target_order))


def rotate_last_srv(targets: list[ChainTargetInfo]) -> list[ChainTargetInfo]:
    """Operator chain surgery when the LASTSRV holder is gone for good
    (updateChain.cc:143-163): move the LASTSRV head to the tail, designate
    the next target as the new authoritative LASTSRV, everything else
    OFFLINE.  No-op unless the head is LASTSRV and the chain has >= 2."""
    if len(targets) < 2 or targets[0].public_state != PublicTargetState.LASTSRV:
        return targets
    new = [ChainTargetInfo(t.target_id, t.node_id, t.public_state)
           for t in targets[1:]]
    moved = targets[0]
    new.append(ChainTargetInfo(moved.target_id, moved.node_id,
                               PublicTargetState.OFFLINE))
    new[0].public_state = PublicTargetState.LASTSRV
    for t in new[1:]:
        t.public_state = PublicTargetState.OFFLINE
    return new


def rotate_as_preferred_order(targets: list[ChainTargetInfo],
                              preferred: list[int]) -> list[ChainTargetInfo]:
    """One step toward the operator-preferred order (updateChain.cc:106-141):
    find the first position whose current target differs from the preferred
    one; if that target is SERVING, rotate it to the tail as OFFLINE (it will
    resync back in at the tail).  Repeated invocations converge the chain to
    the preferred order one resync cycle at a time."""
    pos = {t.target_id: i for i, t in enumerate(targets)}
    for i, want in enumerate(preferred):
        if want not in pos:
            continue
        if pos[want] == i:
            continue
        cur = targets[i]
        if cur.public_state != PublicTargetState.SERVING:
            break
        new = [ChainTargetInfo(t.target_id, t.node_id, t.public_state)
               for j, t in enumerate(targets) if j != i]
        new.append(ChainTargetInfo(cur.target_id, cur.node_id,
                                   PublicTargetState.OFFLINE))
        return new
    return targets


@serde_struct
@dataclass
class ChainOpReq:
    chain_id: int = 0
    target_id: int = 0           # update_chain only
    node_id: int = 0             # update_chain ADD only
    mode: str = ""               # update_chain: "add" | "remove"
    order: list[int] = field(default_factory=list)  # set_preferred_target_order


@serde_struct
@dataclass
class ChainRsp:
    chain: ChainInfo | None = None


@serde_struct
@dataclass
class TargetInfoBlob:
    """Persisted per-target local states (MgmtdTargetInfoPersister analog):
    a restarted/failed-over mgmtd reloads the last known target info instead
    of starting blind until heartbeats repopulate it."""
    states: dict[int, LocalTargetState] = field(default_factory=dict)


@serde_struct
@dataclass
class ClientSessionReq:
    session: ClientSession | None = None


@serde_struct
@dataclass
class ListClientSessionsRsp:
    sessions: list[ClientSession] = field(default_factory=list)


@serde_struct
@dataclass
class NodeOpReq:
    """enableNode/disableNode/unregisterNode/setNodeTags carrier."""
    node_id: int = 0
    tags: list[str] = field(default_factory=list)


@serde_struct
@dataclass
class NodeRsp:
    node: NodeInfo | None = None


@serde_struct
@dataclass
class GetClientSessionReq:
    client_id: str = ""


@serde_struct
@dataclass
class GetClientSessionRsp:
    session: ClientSession | None = None
    found: bool = False


@serde_struct
@dataclass
class UniversalTagsReq:
    tags: list[str] = field(default_factory=list)


@serde_struct
@dataclass
class UniversalTagsRsp:
    tags: list[str] = field(default_factory=list)


@serde_struct
@dataclass
class ConfigVersionsRsp:
    """Per-node-type template fingerprints (crc32c of the TOML): the
    reference's getConfigVersions surface with content hashes as the
    version — equal hash == identical distributed config."""
    versions: dict[str, int] = field(default_factory=dict)


@serde_struct
@dataclass
class OrphanTarget:
    target_id: int = 0
    node_id: int = 0                 # reporter (0 if unknown)
    local_state: LocalTargetState = LocalTargetState.OFFLINE


@serde_struct
@dataclass
class ListOrphanTargetsRsp:
    targets: list[OrphanTarget] = field(default_factory=list)


@serde_struct
@dataclass
class RepairStatus:
    """One scrub scheduler's health report (`admin repair-status` row).

    Scrub runs cluster-side (storage/scrub_scheduler.py), so its health
    reaches mgmtd by PUSH: the scheduler's owner posts status() after
    each tick via report_repair_status; mgmtd keeps the latest row per
    source in memory (liveness-style — re-learned after a restart, same
    contract as last_heartbeat).  Append-only for serde compat."""
    source: str = ""
    ts: float = 0.0                 # mgmtd receive time (server-stamped)
    repair_mode: str = ""
    budget_mbps: float = 0.0
    targets: int = 0
    ticks: int = 0
    stripes_scanned: int = 0
    shards_probed: int = 0
    shards_lost: int = 0
    shards_corrupt: int = 0
    flagged_enqueued: int = 0
    flagged_unresolved: int = 0
    flagged_pending: int = 0
    repaired_stripes: int = 0
    repaired_shards: int = 0
    stripes_failed: int = 0
    bytes_read: int = 0
    bytes_repaired: int = 0
    reduced_shards: int = 0
    fallback_shards: int = 0
    paced_waits: int = 0
    paced_wait_s: float = 0.0

    @classmethod
    def from_status(cls, source: str, status: dict) -> "RepairStatus":
        """Build a row from ScrubScheduler.status(); unknown keys are
        dropped so scheduler and mgmtd can rev independently."""
        row = cls(source=source)
        for k, v in status.items():
            if k not in ("source", "ts") and hasattr(row, k):
                setattr(row, k, v)
        return row


@serde_struct
@dataclass
class ReportRepairStatusReq:
    status: RepairStatus = field(default_factory=RepairStatus)


@serde_struct
@dataclass
class RepairStatusRsp:
    rows: list[RepairStatus] = field(default_factory=list)


@service("Mgmtd")
class MgmtdService:
    """RPC surface (fbs/mgmtd/MgmtdServiceDef.h:3-26 subset)."""

    def __init__(self, state: MgmtdState):
        self.state = state

    async def _require_primary(self):
        if not await self.state.is_primary():
            raise make_error(StatusCode.MGMTD_NOT_PRIMARY,
                             f"mgmtd {self.state.node_id} lost the lease")

    @rpc_method
    async def heartbeat(self, req: HeartbeatReq, payload, conn):
        await self._require_primary()
        st = self.state
        known = st.routing().nodes.get(req.node.node_id)
        if known is not None and known.node_type != req.node.node_type:
            # node ids are cluster-global: a meta server configured with a
            # storage node's id would otherwise flip the record's generation
            # every other heartbeat and demote that node's targets forever
            raise make_error(
                StatusCode.INVALID_ARG,
                f"node id {req.node.node_id} already registered as "
                f"{known.node_type!r}, refusing {req.node.node_type!r}")
        st.last_heartbeat[req.node.node_id] = time.time()
        # generation is PERSISTED with the node record, so restart
        # detection survives an mgmtd restart/failover coinciding with
        # the storage node's restart
        prev_gen = known.generation if known is not None else None
        restarted = (req.node.generation and prev_gen
                     and prev_gen != req.node.generation)
        # status + tags are MGMTD-owned fields: a node's self-report must
        # never stomp an admin disable-node or set-node-tags (the node
        # always reports defaults for them)
        reported = req.node
        if known is not None:
            reported = NodeInfo(**{**req.node.__dict__})
            reported.status = known.status
            reported.tags = list(known.tags)
        if restarted:
            # fast restart (within the heartbeat window): every target
            # this node serves must fall back to SYNCING and resync.
            # The new generation is NOT persisted here — the chains
            # updater saves it atomically with the demotions, so a
            # primary failover can't observe the generation without them.
            for chain in st.routing().chains.values():
                for t in chain.targets:
                    if t.node_id == req.node.node_id:
                        st.restarted_targets.add(t.target_id)
            st.pending_node_saves[req.node.node_id] = reported
        for tid, ls in req.target_states.items():
            st.local_states[int(tid)] = LocalTargetState(ls)
            st.target_reporter[int(tid)] = req.node.node_id
            st.fresh_targets.discard(int(tid))
        st.fresh_targets.update(int(t) for t in req.fresh_targets)
        if not restarted and (known is None
                              or known.address != req.node.address
                              or known.generation != req.node.generation):
            await st.save_node(reported)
            await st.load_routing()
        return HeartbeatRsp(routing_version=st.routing().version,
                            lease_s=st.cfg.heartbeat_timeout_s), b""

    @rpc_method
    async def get_routing_info(self, req: GetRoutingInfoReq, payload, conn):
        info = self.state.routing()
        rsp = GetRoutingInfoRsp()
        if req.known_version < info.version:
            # ISSUE 15: delta-capable callers get only the changed chains
            # when the change log covers their version; everyone else
            # (and any log gap) gets the full map
            delta = self.state.build_delta(req.known_version) \
                if getattr(req, "want_delta", False) else None
            if delta is not None:
                rsp.delta = delta
            else:
                rsp.info = info
        # scorecard piggyback rides even when routing is unchanged —
        # health moves on its own clock (the monitor pull period)
        st = self.state
        if st.health is not None \
                and req.known_health_version < st.health_version:
            rsp.health = st.health
            rsp.health_version = st.health_version
        return rsp, b""

    @rpc_method
    async def cluster_health(self, req: ClusterHealthReq, payload, conn):
        """Admin op: the scorecard the primary last pulled from the
        monitor (what GetRoutingInfoRsp piggybacks)."""
        return ClusterHealthRsp(health=self.state.health,
                                health_version=self.state.health_version), b""

    @rpc_method
    async def set_chains(self, req: SetChainsReq, payload, conn):
        """Admin op: install chains/chain tables (UploadChainTable analog)."""
        await self._require_primary()
        await self.state.save_chains(req.chains, req.tables,
                                     guard_versions=False)
        return OkRsp(), b""

    @rpc_method
    async def list_nodes(self, req, payload, conn):
        """Admin op (ListNodes analog): registered nodes + liveness."""
        st = self.state
        now = time.time()
        rows = []
        for node in st.routing().nodes.values():
            hb = st.last_heartbeat.get(node.node_id, 0.0)
            rows.append(NodeStatus(
                node=node, last_heartbeat_age_s=(now - hb) if hb else -1.0,
                alive=st.node_alive(node.node_id)))
        return ListNodesRsp(rows), b""

    @rpc_method
    async def get_lease(self, req, payload, conn):
        """Who is primary (MgmtdLeaseInfo analog)."""
        lease = await self.state.lease_info()
        return lease, b""

    @rpc_method
    async def report_repair_status(self, req: ReportRepairStatusReq,
                                   payload, conn):
        """Scrub scheduler health push (ISSUE 9): keep the latest row
        per source; ts is server-stamped so skewed client clocks can't
        make a live scrubber look stale."""
        await self._require_primary()
        row = req.status
        row.source = row.source or "scrub"
        row.ts = time.time()
        self.state.repair_statuses[row.source] = row
        return OkRsp(), b""

    @rpc_method
    async def repair_status(self, req, payload, conn):
        """Admin op: latest scrub/repair health rows, one per source."""
        rows = sorted(self.state.repair_statuses.values(),
                      key=lambda r: r.source)
        return RepairStatusRsp(rows=rows), b""

    # ---- chain surgery (admin ops) ----

    async def _load_chain(self, chain_id: int) -> ChainInfo:
        chain = self.state.routing().chain(chain_id)
        if chain is None:
            raise make_error(StatusCode.TARGET_NOT_FOUND, f"chain {chain_id}")
        return chain

    async def _save_chain_checked(self, chain: ChainInfo) -> None:
        """CAS-persist one admin-modified chain; a lost race with the
        background chains updater surfaces as a retryable conflict instead
        of the op silently being reverted."""
        written = await self.state.save_chains([chain])
        if chain.chain_id not in written:
            raise make_error(
                StatusCode.CHAIN_VERSION_MISMATCH,
                f"chain {chain.chain_id} changed concurrently; retry")

    @rpc_method
    async def rotate_last_srv(self, req: ChainOpReq, payload, conn):
        """RotateLastSrvOperation analog (mgmtd/ops/RotateLastSrvOperation.cc)."""
        await self._require_primary()
        chain = await self._load_chain(req.chain_id)
        new_targets = rotate_last_srv(chain.targets)
        if new_targets is chain.targets:
            return ChainRsp(chain=chain), b""
        nxt = ChainInfo(chain.chain_id, chain.chain_ver + 1, new_targets,
                        chain.preferred_target_order)
        await self._save_chain_checked(nxt)
        return ChainRsp(chain=nxt), b""

    @rpc_method
    async def update_chain(self, req: ChainOpReq, payload, conn):
        """Add/remove a target (UpdateChainOperation.cc): add appends as
        OFFLINE (it joins via resync); remove requires the target OFFLINE."""
        await self._require_primary()
        chain = await self._load_chain(req.chain_id)
        if not req.target_id:
            raise make_error(StatusCode.INVALID_ARG, "empty target id")
        targets = [ChainTargetInfo(t.target_id, t.node_id, t.public_state)
                   for t in chain.targets]
        preferred = list(chain.preferred_target_order)
        if req.mode == "add":
            for c in self.state.routing().chains.values():
                if any(t.target_id == req.target_id for t in c.targets):
                    raise make_error(StatusCode.INVALID_ARG,
                                     f"target {req.target_id} already in chain "
                                     f"{c.chain_id}")
            targets.append(ChainTargetInfo(req.target_id, req.node_id,
                                           PublicTargetState.OFFLINE))
            if len(preferred) == len(targets) - 1:
                preferred.append(req.target_id)
        elif req.mode == "remove":
            hit = [t for t in targets if t.target_id == req.target_id]
            if not hit:
                raise make_error(StatusCode.TARGET_NOT_FOUND,
                                 f"target {req.target_id} not in chain")
            if hit[0].public_state != PublicTargetState.OFFLINE:
                raise make_error(
                    StatusCode.INVALID_ARG,
                    f"target {req.target_id} is {hit[0].public_state.name}, "
                    "only OFFLINE targets can be removed")
            targets = [t for t in targets if t.target_id != req.target_id]
            preferred = [t for t in preferred if t != req.target_id]
        else:
            raise make_error(StatusCode.INVALID_ARG, f"mode {req.mode!r}")
        nxt = ChainInfo(chain.chain_id, chain.chain_ver + 1, targets, preferred)
        await self._save_chain_checked(nxt)
        return ChainRsp(chain=nxt), b""

    @rpc_method
    async def set_preferred_target_order(self, req: ChainOpReq, payload, conn):
        """SetPreferredTargetOrderOperation analog: record the operator's
        desired order; rotate_as_preferred_order walks the chain toward it."""
        await self._require_primary()
        chain = await self._load_chain(req.chain_id)
        have = {t.target_id for t in chain.targets}
        if set(req.order) != have:
            raise make_error(StatusCode.INVALID_ARG,
                             f"order {req.order} != chain targets {sorted(have)}")
        nxt = ChainInfo(chain.chain_id, chain.chain_ver + 1,
                        chain.targets, list(req.order))
        await self._save_chain_checked(nxt)
        return ChainRsp(chain=nxt), b""

    @rpc_method
    async def rotate_as_preferred_order(self, req: ChainOpReq, payload, conn):
        """One rotation step toward the preferred order
        (RotateAsPreferredOrderOperation.cc analog)."""
        await self._require_primary()
        chain = await self._load_chain(req.chain_id)
        if not chain.preferred_target_order:
            return ChainRsp(chain=chain), b""
        new_targets = rotate_as_preferred_order(
            chain.targets, chain.preferred_target_order)
        if new_targets is chain.targets:
            return ChainRsp(chain=chain), b""
        nxt = ChainInfo(chain.chain_id, chain.chain_ver + 1, new_targets,
                        chain.preferred_target_order)
        await self._save_chain_checked(nxt)
        return ChainRsp(chain=nxt), b""

    # ---- client sessions ----

    @rpc_method
    async def extend_client_session(self, req: ClientSessionReq, payload, conn):
        """Register/extend a client session (ExtendClientSessionOperation
        analog); sessions are persisted so a mgmtd failover keeps them."""
        await self._require_primary()
        s = req.session
        if s is None or not s.client_id:
            raise make_error(StatusCode.INVALID_ARG, "empty session")
        now = time.time()
        s.last_extend = now

        async def op(txn):
            key = KeyPrefix.CLIENT_SESSION.key(s.client_id.encode())
            raw = await txn.get(key)
            if raw is not None:
                prev: ClientSession = serde.loads(raw)
                s.start = prev.start or now
            else:
                s.start = s.start or now
            txn.set(key, serde.dumps(s))
        await with_transaction(self.state.kv, op)
        return OkRsp(), b""

    @rpc_method
    async def list_client_sessions(self, req, payload, conn):
        async def op(txn):
            return await txn.get_range(
                KeyPrefix.CLIENT_SESSION.value,
                KeyPrefix.CLIENT_SESSION.value + b"\xff", snapshot=True)
        rows = await with_transaction(self.state.kv, op)
        return ListClientSessionsRsp(
            sessions=[serde.loads(v) for _, v in rows]), b""

    # --- node admin ops (MgmtdServiceDef.h:9-16 parity) ---

    async def _node_op(self, node_id: int, mutate) -> NodeInfo:
        """In-txn read-modify-write of a node record + routing version bump.
        Reading the CURRENT record inside the transaction (not the routing
        cache) means a concurrent heartbeat's address/generation save can't
        be reverted — the admin op rebases on whatever committed last."""
        await self._require_primary()
        st = self.state
        key = KeyPrefix.NODE.key(str(node_id).encode())
        out: list[NodeInfo] = []

        async def txn_fn(txn):
            raw = await txn.get(key)
            if raw is None:
                raise make_error(StatusCode.TARGET_NOT_FOUND,
                                 f"node {node_id}")
            updated: NodeInfo = serde.loads(raw)
            mutate(updated)
            txn.set(key, serde.dumps(updated))
            ver = await txn.get(KeyPrefix.ROUTING_VER.key())
            txn.set(KeyPrefix.ROUTING_VER.key(),
                    str(int(ver or 1) + 1).encode())
            out[:] = [updated]
        await with_transaction(st.kv, txn_fn)
        await st.load_routing()
        st._log_change(st.routing().version, ())   # node-only bump
        # rebase any pending restart-save on the admin result: the updater
        # flush would otherwise re-persist the PRE-admin status/tags it
        # captured at heartbeat time (keep its generation — that's the
        # restart-detection payload it exists to deliver).  AFTER
        # load_routing: a heartbeat landing during the reload reads the
        # stale cache and re-captures the pre-admin status; rebasing last
        # covers that window too.
        pend = st.pending_node_saves.get(node_id)
        if pend is not None:
            pend.status = out[0].status
            pend.tags = list(out[0].tags)
        return out[0]

    @rpc_method
    async def enable_node(self, req: NodeOpReq, payload, conn):
        def mutate(n):
            n.status = NodeStatusEnum.ACTIVE
        return NodeRsp(node=await self._node_op(req.node_id, mutate)), b""

    @rpc_method
    async def disable_node(self, req: NodeOpReq, payload, conn):
        """Administrative drain: the chains updater treats the node's
        targets like a dead node's (they walk to chain tail), but the node
        keeps heartbeating — re-enable restores it without a restart."""
        def mutate(n):
            n.status = NodeStatusEnum.DISABLED
        return NodeRsp(node=await self._node_op(req.node_id, mutate)), b""

    @rpc_method
    async def set_node_tags(self, req: NodeOpReq, payload, conn):
        def mutate(n):
            n.tags = list(req.tags)
        return NodeRsp(node=await self._node_op(req.node_id, mutate)), b""

    @rpc_method
    async def unregister_node(self, req: NodeOpReq, payload, conn):
        """Retire a node record.  Refused while any chain still references
        the node — silently dropping a referenced node would strand its
        targets in the chain state machine."""
        await self._require_primary()
        st = self.state
        routing = st.routing()
        if routing.nodes.get(req.node_id) is None:
            raise make_error(StatusCode.TARGET_NOT_FOUND,
                             f"node {req.node_id}")
        for chain in routing.chains.values():
            if any(t.node_id == req.node_id for t in chain.targets):
                raise make_error(
                    StatusCode.INVALID_ARG,
                    f"node {req.node_id} still on chain {chain.chain_id}; "
                    f"update-chain it away first")
        if st.last_heartbeat.get(req.node_id) is not None \
                and st.node_alive(req.node_id):
            # a live node would simply re-register on its next heartbeat,
            # silently undoing this op seconds later
            raise make_error(
                StatusCode.INVALID_ARG,
                f"node {req.node_id} is still heartbeating; stop it (or "
                f"disable-node) first")

        async def op(txn):
            txn.clear(KeyPrefix.NODE.key(str(req.node_id).encode()))
            raw = await txn.get(KeyPrefix.ROUTING_VER.key())
            txn.set(KeyPrefix.ROUTING_VER.key(),
                    str(int(raw or 1) + 1).encode())
        await with_transaction(st.kv, op)
        st.last_heartbeat.pop(req.node_id, None)
        # a pending restart-save would re-create the record on the next
        # updater tick
        st.pending_node_saves.pop(req.node_id, None)
        # reap the retired node's reported-target bookkeeping, or its
        # targets linger in list_orphan_targets forever
        for tid in [t for t, n in st.target_reporter.items()
                    if n == req.node_id]:
            st.target_reporter.pop(tid, None)
            st.local_states.pop(tid, None)
        await st.load_routing()
        st._log_change(st.routing().version, ())   # node-only bump
        return OkRsp(), b""

    @rpc_method
    async def get_client_session(self, req: GetClientSessionReq, payload,
                                 conn):
        async def op(txn):
            return await txn.get(
                KeyPrefix.CLIENT_SESSION.key(req.client_id.encode()),
                snapshot=True)
        raw = await with_transaction(self.state.kv, op)
        return GetClientSessionRsp(
            session=serde.loads(raw) if raw is not None else None,
            found=raw is not None), b""

    @rpc_method
    async def set_universal_tags(self, req: UniversalTagsReq, payload, conn):
        await self._require_primary()

        async def op(txn):
            txn.set(KeyPrefix.UNIVERSAL_TAGS.key(),
                    serde.dumps(list(req.tags)))
        await with_transaction(self.state.kv, op)
        return OkRsp(), b""

    @rpc_method
    async def get_universal_tags(self, req, payload, conn):
        async def op(txn):
            return await txn.get(KeyPrefix.UNIVERSAL_TAGS.key(),
                                 snapshot=True)
        raw = await with_transaction(self.state.kv, op)
        return UniversalTagsRsp(
            tags=serde.loads(raw) if raw is not None else []), b""

    @rpc_method
    async def get_config_versions(self, req, payload, conn):
        from t3fs.ops.codec import crc32c

        async def op(txn):
            return await txn.get_range(KeyPrefix.CONFIG.value,
                                       KeyPrefix.CONFIG.value + b"\xff",
                                       snapshot=True)
        rows = await with_transaction(self.state.kv, op)
        plen = len(KeyPrefix.CONFIG.value)
        return ConfigVersionsRsp(versions={
            k[plen:].decode(): crc32c(v) for k, v in rows}), b""

    @rpc_method
    async def list_orphan_targets(self, req, payload, conn):
        """Targets reported in heartbeats that no chain references
        (ListOrphanTargetsOperation analog) — leftovers of chain surgery /
        aborted migrations an operator should reap."""
        st = self.state
        chained = {t.target_id
                   for c in st.routing().chains.values()
                   for t in c.targets}
        out = [OrphanTarget(target_id=tid,
                            node_id=st.target_reporter.get(tid, 0),
                            local_state=ls)
               for tid, ls in sorted(st.local_states.items())
               if tid not in chained]
        return ListOrphanTargetsRsp(targets=out), b""

    @rpc_method
    async def set_config_template(self, req: SetConfigTemplateReq, payload, conn):
        """Store a per-node-type config template in the KV — the config-
        distribution half of the two-phase bootstrap (reference:
        TwoPhaseApplication.h:42-46, core/app/MgmtdClientFetcher.h)."""
        await self._require_primary()

        async def op(txn):
            txn.set(KeyPrefix.CONFIG.key(req.node_type.encode()),
                    req.toml.encode())
        await with_transaction(self.state.kv, op)
        return OkRsp(), b""

    @rpc_method
    async def get_config_template(self, req: GetConfigTemplateReq, payload, conn):
        async def op(txn):
            return await txn.get(KeyPrefix.CONFIG.key(req.node_type.encode()))
        raw = await with_transaction(self.state.kv, op)
        return GetConfigTemplateRsp(
            toml=raw.decode() if raw is not None else "",
            found=raw is not None), b""


class MgmtdServer:
    """State + service + background loops (chains updater, lease extender)."""

    def __init__(self, kv: KVEngine, node_id: int = 1, address: str = "",
                 cfg: MgmtdConfig | None = None, admin_token: str = ""):
        self.cfg = cfg or MgmtdConfig()
        self.state = MgmtdState(kv, node_id, address, self.cfg)
        self.service = MgmtdService(self.state)
        from t3fs.core.service import AppInfo, CoreService
        self.core = CoreService(AppInfo(node_id, "mgmtd", address),
                                config=self.cfg, kv=kv, admin_token=admin_token)
        self._tasks: list[asyncio.Task] = []
        self._stopped = asyncio.Event()

    @property
    def services(self):
        """Everything to register on the net server (reference registers
        MgmtdService + CoreService, MgmtdServer.cc:33-34)."""
        return [self.service, self.core]

    async def start(self) -> None:
        acquired = await self.state.try_acquire_lease()
        if acquired:
            log.info("mgmtd %d acquired primary lease", self.state.node_id)
        await self.state.load_routing()
        self._tasks = [
            asyncio.create_task(self._chains_updater(), name="mgmtd-chains"),
            asyncio.create_task(self._lease_extender(), name="mgmtd-lease"),
            asyncio.create_task(self._sessions_checker(),
                                name="mgmtd-sessions"),
        ]
        if self.cfg.monitor_address:
            self._tasks.append(asyncio.create_task(
                self._health_puller(), name="mgmtd-health"))

    async def stop(self) -> None:
        self._stopped.set()
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            await reap_task(t, log, t.get_name())

    async def _lease_extender(self) -> None:
        while not self._stopped.is_set():
            await asyncio.sleep(self.cfg.lease_extend_period_s)
            try:
                await self.state.try_acquire_lease()
            except Exception:
                log.exception("lease extension failed")

    async def _chains_updater(self) -> None:
        """Primary-only periodic scan applying the chain state machine
        (MgmtdChainsUpdater.cc:72 analog)."""
        while not self._stopped.is_set():
            await asyncio.sleep(self.cfg.chains_update_period_s)
            try:
                if not await self.state.is_primary():
                    continue
                await self.update_chains_once()
            except Exception:
                log.exception("chains updater failed")

    async def _health_puller(self) -> None:
        """Primary-only pull of the cluster health scorecard from the
        monitor (ISSUE 14): Monitor.health → state.health, version-bumped
        so GetRoutingInfoRsp piggybacks only genuinely newer scorecards.
        Monitor down = keep the last scorecard; its freshness bound makes
        staleness explicit to consumers."""
        from t3fs.monitor.service import HealthReq
        from t3fs.net.client import Client

        cli = Client()
        try:
            while not self._stopped.is_set():
                await asyncio.sleep(self.cfg.health_pull_period_s)
                try:
                    if not await self.state.is_primary():
                        continue
                    rsp, _ = await cli.call(
                        self.cfg.monitor_address, "Monitor.health",
                        HealthReq(window_s=self.cfg.health_window_s),
                        timeout=5.0)
                    health = getattr(rsp, "health", None)
                    if health is None:
                        continue
                    # rollup rows carry the REPORTER's node id; resolve
                    # serving addrs to routing node ids so consumers can
                    # join the scorecard against chain targets
                    addr_to_node = {n.address: n.node_id
                                    for n in self.state.routing().nodes.values()}
                    for nh in health.nodes:
                        nh.node_id = addr_to_node.get(nh.addr, nh.node_id)
                    self.state.health = health
                    self.state.health_version += 1
                except Exception as e:
                    # warning, not exception: a briefly-unreachable
                    # monitor is routine and re-tried next period
                    log.warning("health pull from %s failed: %s",
                                self.cfg.monitor_address, e)
        finally:
            await cli.close()

    async def _sessions_checker(self) -> None:
        """Prune client sessions whose lease expired
        (MgmtdClientSessionsChecker analog)."""
        while not self._stopped.is_set():
            await asyncio.sleep(self.cfg.sessions_check_period_s)
            try:
                if not await self.state.is_primary():
                    continue
                await self.prune_client_sessions_once()
            except Exception:
                log.exception("sessions checker failed")

    async def prune_client_sessions_once(self) -> int:
        """Remove expired sessions; returns count pruned (test hook)."""
        cutoff = time.time() - self.cfg.client_session_ttl_s
        kv = self.state.kv

        async def op(txn):
            rows = await txn.get_range(
                KeyPrefix.CLIENT_SESSION.value,
                KeyPrefix.CLIENT_SESSION.value + b"\xff")
            dead = []
            for k, v in rows:
                s: ClientSession = serde.loads(v)
                if s.last_extend < cutoff:
                    txn.clear(k)
                    dead.append(s.client_id)
            return dead
        dead = await with_transaction(kv, op)
        if dead:
            log.info("pruned %d expired client sessions: %s",
                     len(dead), dead[:8])
        return len(dead)

    async def update_chains_once(self) -> int:
        """One updater tick; returns number of chains changed (test hook).

        Recomputes and retries when a CAS-guarded save loses a race with an
        admin chain op (save_chains skips chains whose persisted version
        moved; node generations only ride on a fully-clean save)."""
        st = self.state
        for _ in range(3):
            routing = st.routing()
            updated = []
            handled: set[int] = set()
            for chain in routing.chains.values():
                alive = {t.node_id: st.node_serviceable(t.node_id)
                         for t in chain.targets}
                nxt = next_chain_state(chain, alive, st.local_states,
                                       restarted=st.restarted_targets,
                                       fresh=st.fresh_targets)
                handled |= {t.target_id for t in chain.targets} \
                    & st.restarted_targets
                if nxt is not None:
                    updated.append(nxt)
                    log.info("chain %d v%d -> v%d: %s", nxt.chain_id,
                             chain.chain_ver, nxt.chain_ver,
                             [(t.target_id, t.public_state.name)
                              for t in nxt.targets])
            pending_nodes = list(st.pending_node_saves.values())
            # liveness -> NodeStatus for non-storage nodes (meta servers):
            # the Distributor must stop hashing duties onto dead/retired
            # peers, and storage liveness is already expressed via chains
            from t3fs.mgmtd.types import NodeStatus as _NS
            for n in routing.nodes.values():
                if n.node_type == "storage":
                    continue
                if n.status == _NS.DISABLED:
                    continue  # admin disable is sticky; liveness can't flip it
                want = _NS.ACTIVE if st.node_alive(n.node_id) else _NS.FAILED
                if n.status != want \
                        and n.node_id not in st.pending_node_saves:
                    flipped = NodeInfo(**{**n.__dict__})
                    flipped.status = want
                    pending_nodes.append(flipped)
            if updated or pending_nodes:
                # demotions and the new node generations land in ONE txn
                written = await st.save_chains(updated, nodes=pending_nodes)
                if len(written) < len(updated):
                    continue  # admin op won the race: recompute from fresh
            # only forget restart flags once the demotions are durably
            # saved — dropping them before a failed save would leave a
            # stale node serving forever
            st.restarted_targets -= handled
            for n in pending_nodes:
                st.pending_node_saves.pop(n.node_id, None)
            if st.local_states != st._persisted_states:
                # target-info persistence (MgmtdTargetInfoPersister analog)
                await st.persist_target_info()
            return len(updated)
        return 0
