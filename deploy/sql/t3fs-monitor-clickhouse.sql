-- t3fs metric store DDL — ClickHouse dialect (production sink).
--
-- Reference analog: deploy/sql/3fs-monitor.sql (the ClickHouse DDL the
-- reference's monitor writes through common/monitor/ClickHouseClient.h).
-- t3fs's ClickHouseClient (t3fs/monitor/clickhouse.py) INSERTs into this
-- table over the HTTP interface with FORMAT JSONEachRow; the column set
-- is IDENTICAL to the sqlite dev DDL (t3fs-monitor.sql) so queries port
-- unchanged — tests/test_monitor.py asserts the sink's wire rows carry
-- exactly these columns.
--
-- Apply (operators):  clickhouse-client --multiquery < t3fs-monitor-clickhouse.sql

CREATE DATABASE IF NOT EXISTS t3fs_monitor;

CREATE TABLE IF NOT EXISTS t3fs_monitor.metrics (
  ts        Float64,
  node_id   Int64,
  node_type String,
  name      String,
  kind      String,
  value     Nullable(Float64),
  payload   String
)
ENGINE = MergeTree
PARTITION BY toDate(toDateTime(ts))
ORDER BY (name, ts)
TTL toDateTime(ts) + INTERVAL 30 DAY;
