-- t3fs monitor_collector metric store DDL.
--
-- Reference analog: deploy/sql/3fs-monitor.sql (ClickHouse DDL for the
-- metric tables that src/monitor_collector/ writes).  t3fs's collector
-- sinks to sqlite (zero-dependency, queryable in place); this file is the
-- canonical schema — t3fs/monitor/service.py applies the identical DDL at
-- startup, and tests/test_deploy.py asserts the two never drift.
--
-- Row shape: one row per recorder sample per collection tick.
--   kind     'count' | 'value' | 'dist' | 'latency'
--   value    the scalar for count/value kinds; p50 for dist/latency
--   payload  full JSON snapshot (tags, p90/p99/min/max/mean for dists)
--
-- Apply manually (operators):  sqlite3 metrics.sqlite < t3fs-monitor.sql

CREATE TABLE IF NOT EXISTS metrics (
  ts REAL NOT NULL,
  node_id INTEGER NOT NULL,
  node_type TEXT NOT NULL,
  name TEXT NOT NULL,
  kind TEXT NOT NULL,
  value REAL,
  payload TEXT NOT NULL
);
CREATE INDEX IF NOT EXISTS metrics_name_ts ON metrics (name, ts);

-- Span rows from the distributed tracer (t3fs/utils/tracing.py), pushed
-- by MonitorReporter via Monitor.report_spans.  One row per finished
-- span; `payload` is the full JSON span (tags, events, remote_parent).
CREATE TABLE IF NOT EXISTS spans (
  ts REAL NOT NULL,
  node_id INTEGER NOT NULL,
  node_type TEXT NOT NULL,
  trace_id INTEGER NOT NULL,
  span_id INTEGER NOT NULL,
  parent_id INTEGER NOT NULL,
  name TEXT NOT NULL,
  kind TEXT NOT NULL,
  t0 REAL NOT NULL,
  dur_s REAL NOT NULL,
  status INTEGER NOT NULL,
  root INTEGER NOT NULL,
  payload TEXT NOT NULL
);
CREATE INDEX IF NOT EXISTS spans_trace ON spans (trace_id);
CREATE INDEX IF NOT EXISTS spans_name_dur ON spans (name, dur_s);
-- arrival-time index: the health plane's rollup pass scans half-open
-- [high-water-mark, now-lag) windows by row ts (t3fs/monitor/rollup.py)
CREATE INDEX IF NOT EXISTS spans_ts ON spans (ts);

-- Time-bucketed per-(node, method) digests written by the continuous
-- rollup pass (cluster health plane, docs/observability.md).  addr !=
-- '' rows are span-sourced (exact percentiles, hop decomposition,
-- worst-trace drill-down, per-size-class tails in payload JSON); addr
-- == '' rows fold serving-side rpc.latency windows (unbiased, SLO
-- input).  Own retention (rollup_max_age_s), independent of the raw
-- tables above.
CREATE TABLE IF NOT EXISTS rollups (
  bucket_ts REAL NOT NULL,
  bucket_s REAL NOT NULL,
  node_id INTEGER NOT NULL,
  addr TEXT NOT NULL,
  method TEXT NOT NULL,
  count INTEGER NOT NULL,
  errors INTEGER NOT NULL,
  p50_s REAL NOT NULL,
  p99_s REAL NOT NULL,
  wire_s REAL NOT NULL,
  queue_s REAL NOT NULL,
  apply_s REAL NOT NULL,
  forward_s REAL NOT NULL,
  worst_dur_s REAL NOT NULL,
  worst_trace_id INTEGER NOT NULL,
  payload TEXT NOT NULL
);
CREATE INDEX IF NOT EXISTS rollups_ts ON rollups (bucket_ts);
CREATE INDEX IF NOT EXISTS rollups_key ON rollups (addr, method, bucket_ts);
