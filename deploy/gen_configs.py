#!/usr/bin/env python3
"""Generate per-node t3fs TOML configs from a small topology spec.

Reference analog: deploy/data_placement/src/setup/gen_chain_table.py plus the
per-binary config triplets under configs/ — here collapsed into one generator
that emits everything a multi-node rollout needs:

    python deploy/gen_configs.py --out /tmp/t3fs-etc \
        --mgmtd 10.0.0.1:9000 \
        --meta 10.0.0.1 10.0.0.2 \
        --storage 10.0.0.3 10.0.0.4 10.0.0.5 10.0.0.6 10.0.0.7 \
        --targets-per-node 2 --replicas 3 --chains 10

Writes mgmtd.toml, kv-*.toml, meta-*.toml, storage-*.toml, fuse.toml,
monitor.toml plus bootstrap.sh (admin-CLI commands to register targets and
install the recovery-balanced chain table).  Review, copy to /etc/t3fs on
each host, then follow deploy/README.md.
"""

from __future__ import annotations

import argparse
import os

MGMTD_PORT = 9000
META_PORT = 9100
STORAGE_PORT = 9200
KV_PORT = 9400
MONITOR_PORT = 9300


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", required=True)
    p.add_argument("--mgmtd", required=True, help="host:port of mgmtd")
    p.add_argument("--meta", nargs="+", required=True, help="meta hosts")
    p.add_argument("--storage", nargs="+", required=True, help="storage hosts")
    p.add_argument("--kv", nargs="*", default=[],
                   help="replicated-KV hosts (first is primary); empty -> "
                        "mgmtd/meta use their local WAL engines")
    p.add_argument("--targets-per-node", type=int, default=2)
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--chains", type=int, default=0,
                   help="0 -> one chain per target")
    p.add_argument("--chunk-size", type=int, default=1 << 20)
    p.add_argument("--data-dir", default="/var/t3fs")
    args = p.parse_args()

    os.makedirs(args.out, exist_ok=True)
    mgmtd_host = args.mgmtd.split(":")[0]
    kv_addrs = [f"{h}:{KV_PORT}" for h in args.kv]
    kv_spec = ("remote:" + ",".join(kv_addrs)) if kv_addrs else None

    def w(name: str, text: str) -> None:
        path = os.path.join(args.out, name)
        with open(path, "w") as f:
            f.write(text)
        print("wrote", path)

    # --- replicated KV nodes (optional FoundationDB-role deployment) ---
    for i, host in enumerate(args.kv):
        role = "primary" if i == 0 else "follower"
        followers = ",".join(a for j, a in enumerate(kv_addrs) if j != i)
        w(f"kv-{i + 1}.toml", f"""\
# t3fs replicated KV node {i + 1} ({host}) — role: {role}
node_id = {i + 1}
listen_host = "0.0.0.0"
listen_port = {KV_PORT}
role = "{role}"
kv = "wal:{args.data_dir}/kv"
followers = "{followers if role == 'primary' else ''}"

[log]
level = "INFO"
file = "/var/log/t3fs/kv.log"
""")

    # --- mgmtd ---
    mgmtd_kv = kv_spec or f"wal:{args.data_dir}/mgmtd-kv"
    w("mgmtd.toml", f"""\
# t3fs mgmtd ({mgmtd_host})
node_id = 1
listen_host = "0.0.0.0"
listen_port = {MGMTD_PORT}
kv = "{mgmtd_kv}"

[service]
heartbeat_timeout_s = 2.0
chains_update_period_s = 0.25
lease_ttl_s = 10.0
lease_extend_period_s = 3.0

[log]
level = "INFO"
file = "/var/log/t3fs/mgmtd.log"
""")

    # --- meta nodes ---
    meta_kv = kv_spec or f"wal:{args.data_dir}/meta-kv"
    if not kv_spec and len(args.meta) > 1:
        print("WARNING: multiple meta servers need a shared KV "
              "(--kv hosts); per-node WAL engines would diverge.")
    for i, host in enumerate(args.meta):
        w(f"meta-{i + 1}.toml", f"""\
# t3fs meta node {i + 1} ({host})
node_id = {100 + i}
listen_host = "0.0.0.0"
listen_port = {META_PORT}
mgmtd_address = "{args.mgmtd}"
kv = "{meta_kv}"
default_chunk_size = {args.chunk_size}
stripe_size = {min(4, len(args.storage))}
gc_period_s = 0.5
session_ttl_s = 3600.0

[log]
level = "INFO"
file = "/var/log/t3fs/meta.log"
""")

    # --- storage nodes ---
    node_ids = []
    for i, host in enumerate(args.storage):
        node_id = 200 + i
        node_ids.append(node_id)
        tids = [node_id * 100 + t for t in range(args.targets_per_node)]
        w(f"storage-{i + 1}.toml", f"""\
# t3fs storage node {i + 1} ({host})
node_id = {node_id}
mgmtd_address = "{args.mgmtd}"
data_dir = "{args.data_dir}/storage"
target_ids = {tids}
engine_backend = "native"

[service]
host = "0.0.0.0"
port = {STORAGE_PORT}
heartbeat_period_s = 0.3
resync_period_s = 0.2
disk_check_period_s = 5.0
maintenance_period_s = 30.0
checksum_backend = "tpu"   # cpu | tpu | null — the codec seam

[log]
level = "INFO"
file = "/var/log/t3fs/storage.log"
""")

    # --- monitor + fuse ---
    w("monitor.toml", f"""\
# t3fs monitor collector
listen_host = "0.0.0.0"
listen_port = {MONITOR_PORT}

[log]
level = "INFO"
file = "/var/log/t3fs/monitor.log"
""")
    w("fuse.toml", f"""\
# t3fs FUSE mount
mountpoint = "/mnt/t3fs"
mgmtd_address = "{args.mgmtd}"

[log]
level = "INFO"
file = "/var/log/t3fs/fuse.log"
""")

    # --- bootstrap script: chain table install via admin CLI ---
    chains = args.chains or len(args.storage) * args.targets_per_node
    nodes_csv = ",".join(str(n) for n in node_ids)
    w("bootstrap.sh", f"""\
#!/bin/sh
# Run ONCE after mgmtd + all storage nodes are up (they self-register via
# heartbeats).  Installs the recovery-balanced chain table.
set -e
ADMIN="python3 -m t3fs.cli.admin --mgmtd {args.mgmtd}"
$ADMIN list-nodes
$ADMIN gen-chains --nodes {nodes_csv} --replicas {args.replicas} \\
    --chains {chains} --apply
$ADMIN routing
""")
    os.chmod(os.path.join(args.out, "bootstrap.sh"), 0o755)


if __name__ == "__main__":
    main()
