#!/usr/bin/env python
"""On-device validation driver (r3 verdict next-step #1).

Two modes:

  python scripts/ondevice.py --probe
      Cheap bounded device probe (subprocess, T3FS_BENCH_PROBE_S deadline).
      ALWAYS appends a dated record to DEVICE_PROBE_LOG.jsonl — two rounds
      died to "the tunnel was wedged when we looked", so the log is the
      proof that the chip was retried throughout the round.

  python scripts/ondevice.py           (= `make on-device`)
      Probe, and if the chip answers run the FULL on-device tier:
        1. bench.py (headline RS(8+2)+CRC32C GB/s/chip),
        2. T3FS_ON_DEVICE=1 pytest tier (pallas codec, codec backend,
           parallel codec — interpret=False, real Mosaic compiles),
        3. the device_sort key-sort stage bench (ROADMAP #1 backlog).
      Writes a dated ONDEVICE_<utc>.json record with all three results.

Exit code is 0 either way (the log entry is the artifact); --check makes
a wedged probe exit 1 for scripting.
"""

from __future__ import annotations

import datetime
import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
PROBE_LOG = REPO / "DEVICE_PROBE_LOG.jsonl"


def utcnow() -> str:
    return datetime.datetime.now(datetime.timezone.utc) \
        .strftime("%Y-%m-%dT%H:%M:%SZ")


def probe() -> dict:
    sys.path.insert(0, str(REPO))
    from bench import _probe_device
    err = _probe_device()
    rec = {"ts": utcnow(), "reachable": err is None}
    if err is not None:
        rec["error"] = err
    with open(PROBE_LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")
    return rec


def _run(cmd: list[str], env: dict | None = None,
         timeout: int = 3600) -> dict:
    e = dict(os.environ)
    if env:
        e.update(env)
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout, env=e, cwd=str(REPO))
        tail = (r.stdout or "").strip().splitlines()[-30:]
        return {"cmd": " ".join(cmd), "rc": r.returncode,
                "tail": "\n".join(tail),
                "stderr_tail": (r.stderr or "").strip()[-2000:]}
    except subprocess.TimeoutExpired:
        return {"cmd": " ".join(cmd), "rc": -1,
                "tail": f"timeout after {timeout}s"}


def full_tier() -> dict:
    out: dict = {"ts": utcnow()}
    out["bench"] = _run([sys.executable, "bench.py"])
    try:
        out["bench_json"] = json.loads(
            out["bench"]["tail"].splitlines()[-1])
    except Exception:
        pass
    out["pytest_on_device"] = _run(
        [sys.executable, "-m", "pytest", "tests/test_pallas_codec.py",
         "tests/test_codec_backend.py", "tests/test_parallel_codec.py",
         "-q", "--no-header"],
        env={"T3FS_ON_DEVICE": "1"}, timeout=2400)
    out["device_sort"] = _run(
        [sys.executable, "-m", "benchmarks.sort_bench",
         "--sort-backend", "device", "--json"],
        timeout=1800)
    return out


def main() -> int:
    rec = probe()
    print(json.dumps(rec))
    if not rec["reachable"]:
        return 1 if "--check" in sys.argv else 0
    if "--probe" in sys.argv:
        return 0
    tier = full_tier()
    stamp = tier["ts"].replace(":", "").replace("-", "")
    out_path = REPO / f"ONDEVICE_{stamp}.json"
    out_path.write_text(json.dumps(tier, indent=1))
    print(f"on-device tier written to {out_path}")
    ok = all(tier[k]["rc"] == 0
             for k in ("bench", "pytest_on_device", "device_sort"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
